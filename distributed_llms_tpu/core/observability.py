"""Structured logging + metrics.

The reference logs with bare ``print()`` throughout (src/master/node.py:36,
197, 206, 215) and its Prometheus/ELK plans (implementation.md:34-41,
:146-157) never landed.  Here: std ``logging`` with an optional JSON
formatter, and an in-process metrics registry (counters, gauges, histogram
summaries) that the coordinator exports over its control-plane endpoint —
tokens/s, p50/p95 hop latency, HBM occupancy, per-stage step time.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": record.created,
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if extra:
            out.update(extra)
        return json.dumps(out)


def get_logger(name: str, json_format: bool = False, level: int = logging.INFO) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        if json_format:
            handler.setFormatter(JsonFormatter())
        else:
            handler.setFormatter(
                logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
            )
        logger.addHandler(handler)
        logger.setLevel(level)
        logger.propagate = False
    return logger


@dataclass
class _Histogram:
    values: list[float] = field(default_factory=list)
    max_keep: int = 4096
    # Cumulative across the full lifetime (Prometheus summary semantics);
    # the percentile window above slides, these never reset.
    total_count: int = 0
    total_sum: float = 0.0

    def observe(self, v: float) -> None:
        if len(self.values) >= self.max_keep:
            # Keep a sliding window: drop oldest half.
            self.values = self.values[self.max_keep // 2 :]
        self.values.append(v)
        self.total_count += 1
        self.total_sum += v

    def summary(self) -> dict[str, float]:
        if not self.values:
            return {"count": 0}
        vs = sorted(self.values)
        n = len(vs)

        def pct(p: float) -> float:
            return vs[min(n - 1, int(p * n))]

        return {
            "count": n,
            "mean": sum(vs) / n,
            "p50": pct(0.50),
            "p95": pct(0.95),
            "p99": pct(0.99),
            "min": vs[0],
            "max": vs[-1],
        }


class Metrics:
    """Thread-safe in-process metrics registry."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)  # guarded-by: self._lock
        self._gauges: dict[str, float] = {}  # guarded-by: self._lock
        self._hists: dict[str, _Histogram] = defaultdict(_Histogram)  # guarded-by: self._lock

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def set_gauges(self, values: dict[str, float]) -> None:
        """Set a family of gauges under one lock acquisition — occupancy
        views (e.g. the KV pool's batcher_pool_* snapshot) publish several
        numbers that should land atomically for a scrape."""
        with self._lock:
            self._gauges.update(values)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._hists[name].observe(value)

    def timer(self, name: str) -> "_Timer":
        return _Timer(self, name)

    def get_counter(self, name: str) -> float:
        """Point read of one counter (0.0 when never incremented) — the
        supervisor's restart accounting and tests read through this
        instead of snapshotting the whole registry."""
        with self._lock:
            return self._counters.get(name, 0.0)

    def get_gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.summary() for k, h in self._hists.items()},
            }

    def prometheus_text(self) -> str:
        """Render the registry in Prometheus exposition format (text/plain
        version 0.0.4).  Histograms export as summaries: quantile series plus
        cumulative _count/_sum.  The reference planned a Prometheus endpoint
        (implementation.md:34-37, :146-157) but never built one."""

        def name_of(raw: str) -> str:
            # Prometheus names: [a-zA-Z_:][a-zA-Z0-9_:]*
            out = "".join(c if c.isalnum() or c == "_" else "_" for c in raw)
            return out if out[:1].isalpha() or out[:1] == "_" else "_" + out

        lines: list[str] = []
        with self._lock:
            for raw, v in sorted(self._counters.items()):
                n = name_of(raw)
                lines.append(f"# TYPE {n} counter")
                lines.append(f"{n} {v}")
            for raw, v in sorted(self._gauges.items()):
                n = name_of(raw)
                lines.append(f"# TYPE {n} gauge")
                lines.append(f"{n} {v}")
            for raw, h in sorted(self._hists.items()):
                n = name_of(raw)
                s = h.summary()
                lines.append(f"# TYPE {n} summary")
                for q in ("p50", "p95", "p99"):
                    if q in s:
                        lines.append(f'{n}{{quantile="0.{q[1:]}"}} {s[q]}')
                lines.append(f"{n}_count {h.total_count}")
                lines.append(f"{n}_sum {h.total_sum}")
        return "\n".join(lines) + "\n"


class _Timer:
    def __init__(self, metrics: Metrics, name: str) -> None:
        self._metrics = metrics
        self._name = name

    def __enter__(self) -> "_Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._metrics.observe(self._name, time.perf_counter() - self._t0)


METRICS = Metrics()

# THE registry of metric names this package emits.  Every name passed to
# METRICS.inc/set_gauge/set_gauges/observe/timer must appear here (or match
# a declared ``*`` pattern — f-string names register VERBATIM as their
# pattern, e.g. ``faults.fired.*``).  graftlint's GL302 pins emission
# sites to this dict, GL305 flags dead entries, and the README metric
# table is generated from it — dashboards can't find what the registry
# doesn't name.
METRIC_DOCS: dict[str, str] = {
    # -- continuous batcher (runtime/batcher.py) --
    "batcher.admitted": "requests admitted into a batch row (counter)",
    "batcher.completed": "requests that finished and published results",
    "batcher.cancelled": "requests cancelled while queued or resident",
    "batcher.shed_total": "queued requests shed at deadline expiry",
    "batcher.preemptions_total": "rows preempted for KV pool pressure",
    "batcher.pages_grown": "KV pages allocated by on-demand row growth",
    "batcher.prefill_chunks": "chunked-prefill bites consumed",
    "batcher.sched.budget_tokens": "per-step token budget available to "
        "fused mixed-schedule dispatches (cumulative; runtime/"
        "scheduler.py)",
    "batcher.sched.prefill_tokens": "prompt tokens consumed by prefill "
        "bites, fused (mixed) and serialized (alternate) alike",
    "batcher.sched.decode_tokens": "decode-token legs dispatched "
        "(span-start live rows x chunk_steps per plain decode/mixed "
        "step — an upper bound on committed tokens: rows finishing "
        "mid-span still occupy their legs until the carry sync)",
    "batcher.sched.stall_rounds": "serialized prefill bites that ran "
        "while decode rows were live — the alternating schedule's "
        "latency spike; the mixed schedule keeps this at zero",
    "batcher.sched.budget_utilization": "per-step token budget fill of "
        "the latest fused dispatch (gauge: (n_active + bite) / "
        "token_budget; exceeds 1.0 when the active decode legs alone "
        "over-subscribe the budget — the floor-1 bite keeps the prefill "
        "progressing)",
    "batcher.prefix_cache.lookups": "automatic prefix-cache lookups",
    "batcher.prefix_cache.hits": "lookups that matched >= 1 cached page",
    "batcher.prefix_cache.hit_tokens": "prompt tokens served from cache",
    "batcher.prefix_cache.miss_tokens": "prompt tokens prefilled fresh",
    "batcher.prefix_cache.hit_rate": "cumulative hit_tokens fraction (gauge)",
    "batcher.prefix_cache.evicted_pages": "cached pages evicted under pressure",
    "batcher.pool.*": "KV page-pool occupancy gauges (free/cached/held/"
                      "total pages, min_available + peak_held watermarks)",
    "batcher.kv_pages_exported": "KV pages gathered for handoff to a "
                                 "decode-role engine",
    "batcher.kv_pages_imported": "handed-off KV pages adopted into the "
                                 "pool (decode-role engine)",
    # -- dispatch-ahead engine loop (overlap) --
    "batcher.overlap.dispatched_ahead": "decode chunks dispatched from the "
                                        "device-resident carry while the "
                                        "previous chunk's host work ran",
    "batcher.overlap.carry_syncs": "decode spans ended by syncing the "
                                   "device carry into the host mirrors "
                                   "(scheduling work was pending)",
    "batcher.overlap.host_lag_seconds": "host work per overlapped chunk "
                                        "(D2H + delivery + digest "
                                        "pre-hashing), concurrent with the "
                                        "next chunk on device (histogram)",
    "batcher.overlap.device_gap_seconds": "host time between a chunk "
                                          "completing and the next chunk "
                                          "dispatching — 0 by construction "
                                          "for dispatched-ahead chunks "
                                          "(histogram)",
    "batcher.overlap.depth": "current dispatch depth: 1 while a chunk is "
                             "dispatched ahead of its predecessor's host "
                             "work, 0 at a carry sync (gauge)",
    # -- paged speculative decoding (batcher spec_chunk) --
    "batcher.spec.rounds": "speculative draft/verify rounds dispatched",
    "batcher.spec.accepted_tokens": "drafted tokens the verify pass "
                                    "committed (bonus/correction tokens "
                                    "excluded)",
    "batcher.spec.rejected_tokens": "drafted tokens the verify pass "
                                    "rejected (rolled back by the "
                                    "pos/length clamp)",
    "batcher.spec.k_downshifts": "rounds dispatched with at least one "
                                 "row's draft length adaptively clamped "
                                 "below spec_k (budget or acceptance-EMA "
                                 "downshift)",
    "batcher.spec.acceptance": "cumulative accepted/(accepted+rejected) "
                               "draft fraction (gauge; per-round "
                               "fractions feed the engine.spec_acceptance "
                               "histogram)",
    # -- grammar-constrained structured output (runtime/constrain.py) --
    "batcher.constrain.rows": "constrained/biased rows admitted (token-mask "
                              "automaton engaged in the decode step)",
    "batcher.constrain.cache_hits": "constraint compiles served from the "
                                    "(constraint, tokenizer) LRU cache",
    "batcher.constrain.cache_misses": "schema/regex -> token-DFA compiles "
                                      "actually built",
    "batcher.constrain.compile_seconds": "wall time of one token-mask "
                                         "automaton compile (histogram)",
    # -- KV memory tiering (int8 pages + host-RAM tier) --
    "batcher.kv_swaps.out": "preemption victims swapped to the host tier "
                            "(raw pages parked instead of recomputed)",
    "batcher.kv_swaps.in": "swapped rows restored to device pages "
                           "(byte-exact, no recompute)",
    "batcher.kv_swaps.fallback": "swap/restore attempts degraded to exact "
                                 "recompute (host budget dry, drop drill, "
                                 "or checksum mismatch)",
    "batcher.host_tier.spilled_pages": "cold cached pages captured to host "
                                       "RAM ahead of LRU eviction",
    "batcher.host_tier.restored_pages": "host-spilled pages scattered back "
                                        "into the pool on a prefix-cache "
                                        "hit",
    "batcher.host_tier.hits": "prefix-cache lookups extended by a "
                              "host-tier restore",
    "batcher.host_tier.spill_evictions": "host-spilled pages dropped for "
                                         "tier budget pressure",
    "batcher.host_tier.*": "host-tier occupancy gauges (budget/used pages, "
                           "swap parcels, spill entries)",
    # -- serving gateway (runtime/server.py) --
    "server.requests": "completion requests accepted past the shed gates",
    "server.disconnects": "requests whose client went away mid-serve",
    "server.request_seconds": "request latency, receipt to close (histogram)",
    "server.ttft_seconds": "time to first token, from receipt (histogram)",
    "server.request_timeouts": "requests that hit their deadline mid-flight",
    "server.requests_shed_total": "requests answered 429/503 unworked",
    "server.requests_shed.*": "shed requests by reason (queue_full, "
                              "cost_gate, queue_deadline)",
    "server.engine_restarts": "supervised engine respawns after a crash",
    "server.requests_retried": "zero-streamed requests re-admitted on restart",
    "server.recovery_seconds": "crash to tokens-flowing-again (histogram)",
    "server.engine_last_chunk_age_s": "watchdog: seconds since last delivery",
    "server.prefill_requests": "prefill-role handoff requests served "
                               "(/v1/prefill)",
    # -- engine / sessions / profiling --
    "engine.generated_tokens": "tokens generated by engine entry points",
    "engine.generate_seconds": "wall seconds per generate call (histogram)",
    "engine.spec_acceptance": "speculative decoding acceptance fraction",
    "kv_spill.spills": "session KV caches spilled to host DRAM",
    "kv_spill.restores": "session KV caches restored to device",
    "kv_spill.host_bytes": "bytes of session KV resident on host (gauge)",
    "kv_spill.resident_sessions": "session caches resident in HBM (gauge)",
    "kv_spill.spilled_sessions": "session caches parked on host (gauge)",
    "*.step_seconds": "per-StepTimer step latency (histogram; name prefix "
                      "is the timer's, e.g. engine.generate)",
    "*.tokens_per_second": "per-StepTimer sliding-window throughput gauge",
    # -- replica fleet router (runtime/router.py + cluster/fleet.py) --
    "router.requests": "requests through the router front door",
    "router.placements": "placement decisions onto a replica",
    "router.affinity_hits": "placements that followed prefix-cache affinity",
    "router.failovers": "zero-streamed requests re-placed after a replica "
                        "failure (crash/stall/partition/drain straggler)",
    "router.failover_seconds": "replica failure observed to the re-placed "
                               "request answered (histogram)",
    "router.retries_exhausted": "requests 503'd after the failover budget",
    "router.failed_streamed": "partially-streamed requests failed with "
                              "engine_error (deltas cannot be retracted)",
    "router.replicas_healthy": "replicas currently routable (gauge)",
    "router.committed_tokens.*": "router-side committed token mass per "
                                 "replica (gauge; placement load signal)",
    "router.replica_kills": "replicas killed (chaos or real death observed)",
    "router.drains": "replica drains started (rolling restart)",
    "router.respawns": "replica respawns completed",
    # -- disaggregated prefill/decode (router + cluster/kv_transfer.py) --
    "router.handoffs": "prefill handoffs attempted (disaggregated mode)",
    "router.handoff_skips": "handoffs skipped because the decode replica "
                            "already holds the prompt's page run "
                            "(epoch-valid affinity)",
    "router.handoff_fallbacks": "handoffs degraded to colocated prefill",
    "router.handoff_fallbacks.*": "handoff fallbacks by reason (timeout, "
                                  "error, rejected, digest_mismatch, "
                                  "no_prefill_replica, no_kv_target)",
    "router.handoff_seconds": "prefill + verified transfer latency, "
                              "handoff start to pages landed (histogram)",
    "router.handoff_bytes": "KV payload bytes shipped by completed "
                            "handoffs",
    "xfer.sends": "KV transfer attempts (sender side)",
    "xfer.retries": "KV transfer attempts retried after timeout/NACK",
    "xfer.bytes": "KV transfer frame bytes written to the wire",
    "xfer.send_seconds": "one transfer's send->ack latency incl. retries "
                         "(histogram)",
    "xfer.verify_failures": "KV payloads rejected by checksum/digest "
                            "verification",
    "xfer.dup_deliveries": "duplicate KV deliveries absorbed idempotently",
    # -- cluster control plane --
    "worker.errors": "commands answered with a structured ERROR reply "
                     "(the coordinator's task-retry trigger)",
    "coordinator.workers": "registered workers (gauge)",
    "coordinator.evictions": "workers evicted (heartbeat/connection loss)",
    "coordinator.tasks_dispatched": "tasks sent to workers",
    "coordinator.tasks_completed": "tasks answered with RESULT",
    "coordinator.tasks_retried": "tasks requeued after worker failure",
    "coordinator.tasks_failed": "tasks failed after max attempts",
    "coordinator.shards_reassigned": "shards moved off evicted workers",
    # -- multi-tenant QoS (runtime/scheduler.py + runtime/server.py) --
    "tenant.requests.*": "requests accepted past every shed gate, per "
                         "tenant",
    "tenant.admitted_tokens.*": "admission-time token mass (prompt + "
                                "budget) accepted per tenant — the "
                                "rate-quota gate's currency",
    "tenant.shed.*": "requests shed 429 by the per-tenant token-rate "
                     "quota gate (each carries the tenant's own "
                     "Retry-After)",
    "tenant.vtc.*": "weighted-fair virtual token counter per tenant "
                    "(gauge; runtime/scheduler.py TenantScheduler — "
                    "admission serves the lowest counter first)",
    "tenant.resident_rows.*": "batch rows currently resident per tenant "
                              "(gauge; capped by tenant_max_rows)",
    # -- elastic fleet autoscaling (cluster/autoscale.py) --
    "autoscale.replicas": "live (non-dead) replicas in the fleet (gauge)",
    "autoscale.load": "committed token mass over aggregate routable KV "
                      "capacity — the scale signal (gauge)",
    "autoscale.queue_depth": "router in-flight proxies summed over "
                             "routable replicas (gauge)",
    "autoscale.scale_ups": "replicas added by the autoscaler",
    "autoscale.scale_downs": "replicas drained away by the autoscaler",
    "autoscale.scale_failures": "scale actions that failed or were "
                                "vetoed (injected or real provision "
                                "failure) — the fleet kept its size",
    "autoscale.scale_seconds": "wall time of one scale action, decision "
                               "to done (histogram; up = boot + first "
                               "healthy wait, down = graceful drain)",
    "autoscale.replicas_added": "replicas registered by "
                                "ReplicaFleet.add_replica",
    "autoscale.replicas_removed": "replicas drained away by "
                                  "ReplicaFleet.remove_replica",
    # -- fleet control plane (runtime/router.py, ISSUE 18) --
    "router.ledger.charges": "admissions charged to the router's fleet "
                             "tenant ledger at placement (the one "
                             "admission-commit point)",
    "router.ledger.charged_tokens": "token mass (prompt + budget) charged "
                                    "to the fleet ledger",
    "router.ledger.refunds": "fleet-ledger charges refunded — the request "
                             "shed or failed without service rendered",
    "router.ledger.sheds": "requests shed 429 by the fleet-ledger gate "
                           "(each carries the tenant's own fleet-ledger "
                           "Retry-After)",
    "router.ledger.shed.*": "fleet-ledger sheds per tenant",
    "router.ledger.bypasses": "requests that bypassed the fleet-ledger "
                              "gate (the router.ledger drop drill) — the "
                              "replica gateways' loose backstop still "
                              "meters them, never a silent unmetered path",
    "router.ledger.tenants": "tenants live in the fleet ledger map "
                             "(gauge; cardinality-capped)",
    "directory.lookups": "fleet prefix-digest directory lookups at "
                         "placement (cold replica, warm sibling?)",
    "directory.hits": "lookups that found an epoch-valid sibling holding "
                      "a cached run the placed replica lacks",
    "directory.stale_drops": "directory entries dropped lazily at lookup "
                             "(epoch mismatch — the holder drained or "
                             "respawned since recording)",
    "directory.pulls": "cross-replica KV pulls attempted (sibling cache "
                       "-> placed replica over the checksummed KV_PAGES "
                       "plane)",
    "directory.pulled_pages": "KV pages landed on the placed replica by "
                              "completed cross-replica pulls",
    "directory.pull_bytes": "KV payload bytes shipped by completed pulls",
    "directory.pull_seconds": "one pull's cached-export + verified "
                              "transfer latency (histogram)",
    "directory.pull_fallbacks": "pulls degraded to local recompute "
                                "(byte-exact, just slower)",
    "directory.pull_fallbacks.*": "pull fallbacks by reason (stale, "
                                  "not_cached, error, timeout, rejected, "
                                  "no_kv_target)",
    # -- disaggregated autoscaling (cluster/autoscale.py, per tier) --
    "autoscale.*.replicas": "live replicas in the tier (gauge; * = "
                            "prefill/decode)",
    "autoscale.*.load": "the tier's scale signal (gauge): decode = "
                        "committed-token mass over tier KV capacity, "
                        "prefill = in-flight handoffs per replica",
    "autoscale.*.scale_ups": "replicas added to the tier by the "
                             "autoscaler",
    "autoscale.*.scale_downs": "replicas drained away from the tier "
                               "(graceful-only)",
    "autoscale.*.scale_failures": "tier scale actions that failed or "
                                  "were vetoed — the tier kept its size",
    # -- fault injection (runtime/faults.py) --
    "faults.fired": "injected faults triggered, total",
    "faults.fired.*": "injected faults triggered, by action",
}
