"""Device-mesh construction.

The reference's "topology" is a hub-and-spoke star over TCP with round-robin
shard->worker assignment (src/master/node.py:93-102, :256-269).  Here topology
is a first-class `jax.sharding.Mesh` with named axes; all tensor traffic rides
compiled XLA collectives over ICI instead of sockets.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .config import MeshConfig

__all__ = ["build_mesh", "mesh_from_devices", "local_sharding", "replicated"]


def build_mesh(cfg: MeshConfig, devices: list | None = None) -> Mesh:
    """Build a Mesh with axes (data, pipe, model, seq, expert).

    Axis sizes multiply to the device count.  Axis order puts ``model`` and
    ``seq`` innermost so tensor-parallel and ring collectives ride the
    fastest ICI links; ``data`` and ``pipe`` are outermost and may cross DCN
    on multi-slice deployments.
    """
    devices = devices if devices is not None else jax.devices()
    if cfg.num_devices != len(devices):
        raise ValueError(
            f"mesh shape {cfg.shape} needs {cfg.num_devices} devices, "
            f"got {len(devices)}"
        )
    dev_array = np.asarray(devices).reshape(cfg.shape)
    return Mesh(dev_array, cfg.axis_names)


def mesh_from_devices(axis_sizes: dict[str, int], devices: list | None = None) -> Mesh:
    """Build a mesh from an explicit {axis: size} dict (axes not named get 1)."""
    cfg = MeshConfig(**axis_sizes)
    return build_mesh(cfg, devices)


def local_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
