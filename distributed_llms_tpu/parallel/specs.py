"""Partition-spec rules: which mesh axis shards which param/activation axis.

Successor of the reference's shard assignment (round-robin shard->worker,
src/master/node.py:84-104): "distribution" here is `jax.device_put` with a
`NamedSharding` — weights go host->HBM once and XLA inserts the collectives
(Megatron-style all-reduce for tensor parallelism) instead of tensors
transiting a master over TCP (SURVEY §2.4).

Conventions:
- stacked layer axis L    -> 'pipe'  (pipeline stages own layer blocks)
- attention head axis     -> 'model' (tensor parallelism; KV heads only when
                                      divisible — GQA with few KV heads
                                      replicates KV, shards Q)
- MLP hidden axis F       -> 'model'
- vocab axis              -> 'model' (Megatron-style sharded embed/unembed)
- batch axis              -> 'data'
- sequence axis           -> 'seq'   (ring attention path, ops/ring.py)
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.config import ModelConfig

Params = Any


def _axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape.get(name, 1)


def param_specs(cfg: ModelConfig, mesh: Mesh) -> Params:
    """PartitionSpec pytree matching models.model param trees."""
    tp = _axis_size(mesh, "model")
    # The stacked layer axis shards over 'pipe' only when it divides evenly;
    # an uneven split (e.g. 3 layers over pipe=2) would leave XLA padding a
    # ragged shard on every block leaf — replicate instead and let the
    # staged pipeline path (parallel.api) do its own stage packing.
    # tools.graftcheck GC2 pins this for every preset x mesh.
    pipe_sz = _axis_size(mesh, "pipe")
    pipe = "pipe" if pipe_sz > 1 and cfg.num_layers % pipe_sz == 0 else None
    # Shard head axes only when divisible (e.g. GQA KV heads may be < tp).
    q_ax = "model" if cfg.num_heads % max(tp, 1) == 0 else None
    kv_ax = "model" if cfg.num_kv_heads % max(tp, 1) == 0 else None
    vocab_ax = "model" if cfg.vocab_size % max(tp, 1) == 0 else None
    f_ax = "model" if cfg.intermediate_size % max(tp, 1) == 0 else None

    specs: Params = {
        "embed": {"wte": P(vocab_ax, None)},
        "final_norm": {"scale": P(None)},
    }
    attn = {
        "wq": P(pipe, None, q_ax, None),
        "wk": P(pipe, None, kv_ax, None),
        "wv": P(pipe, None, kv_ax, None),
        "wo": P(pipe, q_ax, None, None),
    }
    if cfg.qkv_bias or cfg.family in ("gpt2", "opt", "neox"):
        # q/k/v biases shard with their head axes (gpt2/opt/neox always
        # carry them; llama only in the Qwen2-style qkv_bias layout).
        attn.update(
            bq=P(pipe, q_ax, None), bk=P(pipe, kv_ax, None),
            bv=P(pipe, kv_ax, None),
        )
    if cfg.family in ("gpt2", "opt", "neox"):
        if cfg.family != "neox":  # neox is rotary — no position table
            specs["embed"]["wpe"] = P(None, None)
        specs["final_norm"]["bias"] = P(None)
        attn["bo"] = P(pipe, None)
        mlp = {
            "w_in": P(pipe, None, f_ax), "b_in": P(pipe, f_ax),
            "w_out": P(pipe, f_ax, None), "b_out": P(pipe, None),
        }
        norm = {"scale": P(pipe, None), "bias": P(pipe, None)}
    elif cfg.num_experts > 0:
        # MoE: expert-stacked weights shard over 'expert' (expert
        # parallelism); the hidden axis can still shard over 'model'.
        ep_size = _axis_size(mesh, "expert")
        ep = "expert" if ep_size > 1 and cfg.num_experts % ep_size == 0 else None
        mlp = {
            "router": P(pipe, None, None),
            "w_gate": P(pipe, ep, None, f_ax), "w_up": P(pipe, ep, None, f_ax),
            "w_down": P(pipe, ep, f_ax, None),
        }
        norm = {"scale": P(pipe, None)}
    else:
        mlp = {
            "w_gate": P(pipe, None, f_ax), "w_up": P(pipe, None, f_ax),
            "w_down": P(pipe, f_ax, None),
        }
        norm = {"scale": P(pipe, None)}
    specs["blocks"] = {"ln1": dict(norm), "ln2": dict(norm), "attn": attn, "mlp": mlp}
    if not cfg.tie_embeddings:
        specs["lm_head"] = {"w": P(None, vocab_ax)}
    return specs


def page_pool_specs(cfg: ModelConfig, mesh: Mesh, kv_bits: int = 16,
                    row_dtype: str | None = None) -> Any:
    """PartitionSpec pytree matching the paged KV pool (runtime/batcher.py
    ``_paged_pool``): data leaves [L, NB, BLK, KVH, HD] shard the KV-head
    axis over 'model' (Megatron-style tensor parallelism — each chip holds
    its heads' slice of every page, so per-chip pool bytes divide by tp);
    int8 absmax scales [L, NB, BLK, KVH] shard the same axis.  Pages are
    shared across rows (prefix cache, handoff imports), so the page axis
    never shards over 'data' — scheduling state replicates instead.
    Non-divisible KV heads replicate (the batcher REJECTS that combination
    up front; the spec mirrors param_specs' degrade convention so the
    graftcheck GC2 audit stays total over the mesh ladder)."""
    from ..models.model import KVCache, QuantKVCache

    tp = _axis_size(mesh, "model")
    kv_ax = "model" if cfg.num_kv_heads % max(tp, 1) == 0 else None
    data = P(None, None, None, kv_ax, None)
    if kv_bits == 8:
        scale = P(None, None, None, kv_ax)
        # row_dtype is QuantKVCache's STATIC pytree metadata: the spec
        # tree must carry the pool's value or tree.map over (pool, specs)
        # rejects the structures as different node types.
        import jax.numpy as jnp

        return QuantKVCache(
            k=data, v=data, k_scale=scale, v_scale=scale,
            row_dtype=row_dtype or jnp.dtype(cfg.dtype).name,
        )
    return KVCache(k=data, v=data)


def shard_params(params: Params, cfg: ModelConfig, mesh: Mesh) -> Params:
    """Place a param tree onto the mesh (host -> HBM once, no sockets)."""
    specs = param_specs(cfg, mesh)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def batch_spec() -> P:
    return P("data", None)
