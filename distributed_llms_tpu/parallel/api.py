"""ParallelModel: one object that places a model on a mesh and runs it.

This is the TPU-native successor of the reference's assign/distribute pair
(`assign_shards` round-robin at src/master/node.py:84-104 and
`distribute_shards` shipping pickled bytes over TCP at :106-115): assignment
becomes PartitionSpecs (specs.py + stages.py), distribution becomes
``jax.device_put`` onto the mesh, and execution composes

- data parallelism   : batch sharded over 'data' (GSPMD)
- tensor parallelism : heads/hidden sharded over 'model' (GSPMD collectives)
- pipeline           : blocks staged over 'pipe' (shard_map + ppermute)

behind a single ``forward`` with the same signature family as
``models.model.forward`` so the runtime decode loop plugs in unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.config import MeshConfig, ModelConfig
from ..models import model as model_lib
from ..models.model import KVCache
from . import pipeline as pipeline_lib
from . import specs as specs_lib

Params = Any


def staged_param_specs(cfg: ModelConfig, mesh: Mesh) -> Params:
    """Specs for a tree whose blocks have been reshaped [L,...] ->
    [pipe, L/pipe, ...]: prepend 'pipe' to block specs, drop it elsewhere."""
    base = specs_lib.param_specs(cfg, mesh)

    def retag(p: P) -> P:
        # base block specs lead with the layer axis ('pipe' or None); staged
        # trees get an explicit leading stage axis sharded over 'pipe'.
        rest = tuple(p)[1:] if len(p) else ()
        return P("pipe", None, *rest)

    out = dict(base)
    out["blocks"] = jax.tree.map(
        retag, base["blocks"], is_leaf=lambda x: isinstance(x, P)
    )
    return out


@dataclass(frozen=True)
class ParallelModel:
    """Mesh-placed model.  Build with :func:`make_parallel_model`."""

    cfg: ModelConfig
    mesh: Mesh
    num_microbatches: int = 1
    kv_dtype: str | None = None  # KV-cache dtype override (default cfg.dtype)

    @property
    def num_stages(self) -> int:
        return self.mesh.shape.get("pipe", 1)

    @property
    def pipelined(self) -> bool:
        return self.num_stages > 1

    @property
    def seq_parallel(self) -> bool:
        return self.mesh.shape.get("seq", 1) > 1

    # -- placement ---------------------------------------------------------

    def shard_params(self, params: Params) -> Params:
        """Stage (if pipelined) and place params onto the mesh."""
        if self.pipelined:
            params = dict(params)
            params["blocks"] = pipeline_lib.split_stages(params["blocks"], self.num_stages)
            specs = staged_param_specs(self.cfg, self.mesh)
        else:
            specs = specs_lib.param_specs(self.cfg, self.mesh)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)), params, specs
        )

    def init_cache(self, batch: int, max_len: int) -> KVCache:
        cfg = self.cfg
        kvh, hd = cfg.num_kv_heads, cfg.head_dim_
        tp = self.mesh.shape.get("model", 1)
        kv_ax = "model" if kvh % max(tp, 1) == 0 else None
        if self.pipelined:
            p, lp = self.num_stages, cfg.num_layers // self.num_stages
            shape = (p, lp, batch, max_len, kvh, hd)
            spec = P("pipe", None, "data", None, kv_ax, None)
        else:
            shape = (cfg.num_layers, batch, max_len, kvh, hd)
            spec = P(None, "data", None, kv_ax, None)
        sharding = NamedSharding(self.mesh, spec)
        # with_sharding_constraint works both eagerly and under jit (the
        # decode loop allocates its cache inside generate_tokens' trace).
        z = jax.lax.with_sharding_constraint(
            jnp.zeros(shape, jnp.dtype(self.kv_dtype or cfg.dtype)), sharding
        )
        return KVCache(k=z, v=z)

    # -- adapters for runtime.generate (hashable bound methods; frozen
    # dataclass => stable hash => jit cache hits across calls) --------------

    def as_forward_fn(self):
        return self._forward_adapter

    def as_make_cache(self):
        return self._make_cache_adapter

    def _forward_adapter(
        self, params, cfg, tokens, positions=None, cache=None,
        cache_index=None, attn_mask=None,
    ):
        del cfg  # self.cfg is authoritative
        return self.forward(
            params, tokens, positions=positions, cache=cache,
            cache_index=cache_index, attn_mask=attn_mask,
        )

    def _make_cache_adapter(self, cfg, batch, max_len):
        del cfg
        return self.init_cache(batch, max_len)

    # -- execution ---------------------------------------------------------

    def _seq_forward(self, params, tokens, positions, remat):
        """Full forward under shard_map over {'seq'}: sequence axis sharded,
        global positions passed through so RoPE/causality stay correct;
        attention runs the ppermute ring (ops/ring.py) or, when the user set
        attn_impl='ulysses', the all-to-all head scatter (ops/ulysses.py);
        'data'/'model' axes remain GSPMD-auto inside the body."""
        cfg = _seq_cfg(self.cfg)
        b, t = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))

        def body(params, tokens, positions):
            logits, _ = model_lib.forward(
                params, cfg, tokens, positions=positions, remat=remat
            )
            return logits

        return jax.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq", None),
            axis_names={"seq"},
        )(params, tokens, positions)

    def forward(
        self,
        params: Params,
        tokens: jax.Array,
        positions: jax.Array | None = None,
        cache: KVCache | None = None,
        cache_index: jax.Array | None = None,
        attn_mask: jax.Array | None = None,
        remat: bool = False,
        return_aux: bool = False,
    ) -> tuple[jax.Array, KVCache | None] | tuple[jax.Array, KVCache | None, jax.Array]:
        """Same contract as models.model.forward, but mesh-parallel.
        ``return_aux`` (MoE load-balance loss) flows through on the
        GSPMD paths; the pipeline/seq shard_map schedules return aux=0 —
        train MoE with data/model/expert axes."""
        cfg = self.cfg
        if (
            self.seq_parallel
            and cache is None
            and not self.pipelined
            and attn_mask is None
        ):
            # Long-context path (SURVEY §5.7): sequence sharded over 'seq',
            # ring attention rotates KV blocks over ICI.  Decode-with-cache
            # and custom-mask calls fall through to the dense path (the ring
            # handles causal masking only; ring targets prefill/training).
            logits = self._seq_forward(params, tokens, positions, remat)
            return (logits, None, jnp.float32(0.0)) if return_aux else (logits, None)
        cfg = _local_cfg(cfg)
        if not self.pipelined:
            return model_lib.forward(
                params, cfg, tokens, positions=positions, cache=cache,
                cache_index=cache_index, remat=remat, attn_mask=attn_mask,
                return_aux=return_aux,
            )

        b, t = tokens.shape
        if positions is None:
            base = cache_index if cache_index is not None else 0
            positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32) + base, (b, t))
        x = model_lib.embed(params, cfg, tokens, positions)
        y, new_cache = pipeline_lib.pipeline_blocks(
            self.mesh, cfg, params["blocks"], x, positions,
            num_microbatches=self.num_microbatches,
            cache_k=cache.k if cache is not None else None,
            cache_v=cache.v if cache is not None else None,
            cache_index=cache_index, attn_mask=attn_mask, remat=remat,
        )
        logits = model_lib.unembed(params, cfg, y)
        new = None if cache is None else KVCache(k=new_cache[0], v=new_cache[1])
        return (logits, new, jnp.float32(0.0)) if return_aux else (logits, new)


def _seq_cfg(cfg: ModelConfig) -> ModelConfig:
    """Pick the sequence-parallel attention impl for the shard_map body:
    the user's 'ulysses' is kept, anything else becomes the ring."""
    import dataclasses

    if cfg.attn_impl == "ulysses":
        return cfg
    return dataclasses.replace(cfg, attn_impl="ring")


def _local_cfg(cfg: ModelConfig) -> ModelConfig:
    """Strip sequence-parallel impls for paths that run *outside* shard_map
    (decode-with-cache, pipeline stages): 'ring'/'ulysses' need a bound seq
    axis and would raise; they degrade to the dense dot path."""
    import dataclasses

    if cfg.attn_impl in ("ring", "ulysses"):
        return dataclasses.replace(cfg, attn_impl="dot")
    return cfg


def make_parallel_model(
    cfg: ModelConfig, mesh_cfg: MeshConfig, num_microbatches: int = 1,
    devices: list | None = None, kv_dtype: str | None = None,
) -> ParallelModel:
    from ..core.mesh import build_mesh

    mesh = build_mesh(mesh_cfg, devices)
    if mesh_cfg.pipe > 1 and cfg.num_layers % mesh_cfg.pipe:
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by pipe {mesh_cfg.pipe}"
        )
    if mesh_cfg.pipe > 1 and mesh_cfg.seq > 1:
        # The ring path replaces the pipeline schedule; a seq axis alongside
        # pipe would silently hold inert replicas instead of sharding sequence.
        raise ValueError(
            f"seq={mesh_cfg.seq} cannot combine with pipe={mesh_cfg.pipe}: "
            "ring attention and the pipeline schedule are alternative "
            "shardings of the layer loop — use one, with 'data'/'model' axes"
        )
    return ParallelModel(
        cfg=cfg, mesh=mesh, num_microbatches=num_microbatches, kv_dtype=kv_dtype
    )
