"""ParallelModel: one object that places a model on a mesh and runs it.

This is the TPU-native successor of the reference's assign/distribute pair
(`assign_shards` round-robin at src/master/node.py:84-104 and
`distribute_shards` shipping pickled bytes over TCP at :106-115): assignment
becomes PartitionSpecs (specs.py + stages.py), distribution becomes
``jax.device_put`` onto the mesh, and execution composes

- data parallelism   : batch sharded over 'data' (GSPMD)
- tensor parallelism : heads/hidden sharded over 'model' (GSPMD collectives)
- pipeline           : blocks staged over 'pipe' (shard_map + ppermute)

behind a single ``forward`` with the same signature family as
``models.model.forward`` so the runtime decode loop plugs in unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import jaxcompat
from ..core.config import MeshConfig, ModelConfig
from ..models import model as model_lib
from ..models.model import KVCache
from . import pipeline as pipeline_lib
from . import specs as specs_lib

Params = Any


def staged_param_specs(cfg: ModelConfig, mesh: Mesh) -> Params:
    """Specs for a tree whose blocks have been reshaped [L,...] ->
    [pipe, L/pipe, ...]: prepend 'pipe' to block specs, drop it elsewhere."""
    base = specs_lib.param_specs(cfg, mesh)

    def retag(p: P) -> P:
        # base block specs lead with the layer axis ('pipe' or None); staged
        # trees get an explicit leading stage axis sharded over 'pipe'.
        rest = tuple(p)[1:] if len(p) else ()
        return P("pipe", None, *rest)

    out = dict(base)
    out["blocks"] = jax.tree.map(
        retag, base["blocks"], is_leaf=lambda x: isinstance(x, P)
    )
    return out


def _axis_sz(mesh: Mesh, name) -> int:
    """Size of a PartitionSpec entry (a name or tuple of names) on a mesh."""
    if name is None:
        return 1
    names = name if isinstance(name, tuple) else (name,)
    sz = 1
    for n in names:
        sz *= mesh.shape.get(n, 1)
    return sz


def _place_quantized(leaf, spec: P, mesh: Mesh, path: str):
    """Shard a QuantizedTensor under the plain weight's PartitionSpec.

    data shards exactly like the weight (for int4 the pack axis holds
    adjacent-row pairs, so a contiguous shard of packed rows unpacks to the
    same contiguous rows — exact).  scale has the weight's shape with the
    last axis in block units; when the spec shards that last axis, scales
    are refined (each block's scale repeated k times = block size / k —
    numerically identical) until shard boundaries land on block boundaries.
    Un-shardable layouts replicate the leaf, loudly.
    """
    from ..checkpoint.quantize import QuantizedTensor
    from ..core.observability import get_logger

    data, scale = leaf.data, leaf.scale
    s = tuple(spec)
    s = s + (None,) * (data.ndim - len(s))  # pad to rank; trailing = replicated

    def replicate(reason: str):
        get_logger("parallel").warning(
            "quantized leaf %s cannot shard under %s (%s); replicating",
            path, spec, reason,
        )
        rep = NamedSharding(mesh, P())
        return QuantizedTensor(
            data=jax.device_put(data, rep), scale=jax.device_put(scale, rep),
            bits=leaf.bits, orig_shape=leaf.orig_shape, pack_axis=leaf.pack_axis,
        )

    pack_ax = data.ndim + leaf.pack_axis if leaf.bits == 4 else None
    # Divisibility of every sharded data axis (jax would raise; we want the
    # replicate fallback instead).
    for ax, name in enumerate(s):
        if _axis_sz(mesh, name) > 1 and data.shape[ax] % _axis_sz(mesh, name):
            return replicate(f"data axis {ax} ({data.shape[ax]}) % shards")
    last = data.ndim - 1
    tp_last = _axis_sz(mesh, s[last])
    if tp_last > 1 and pack_ax == last:
        return replicate("spec shards the int4 pack axis at the last dim")
    if tp_last > 1:
        dim = data.shape[last]  # last axis is never int4-packed here
        n_blocks = scale.shape[-1]
        block = dim // n_blocks
        per_shard = dim // tp_last
        if per_shard % block:
            # Refine: new block g divides both the old block and the shard
            # width, so each shard holds whole (finer) blocks.
            import math

            g = math.gcd(block, per_shard)
            scale = jnp.repeat(scale, block // g, axis=-1)
    # scale has data's rank (last axis in block units; the int4 pack axis is
    # 2x data's, divisible whenever data's is) — the same spec applies.
    return QuantizedTensor(
        data=jax.device_put(data, NamedSharding(mesh, P(*s))),
        scale=jax.device_put(scale, NamedSharding(mesh, P(*s))),
        bits=leaf.bits, orig_shape=leaf.orig_shape, pack_axis=leaf.pack_axis,
    )


def _place_tree(params: Params, specs: Params, mesh: Mesh) -> Params:
    """device_put a param tree onto the mesh, keeping QuantizedTensor leaves
    quantized-resident (sharded data+scale) instead of rehydrating."""
    from ..checkpoint.quantize import QuantizedTensor

    is_q = lambda x: isinstance(x, QuantizedTensor)  # noqa: E731
    spec_by_path = {
        jax.tree_util.keystr(kp): s
        for kp, s in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )[0]
    }

    def place(kp, leaf):
        path = jax.tree_util.keystr(kp)
        spec = spec_by_path[path]
        if is_q(leaf):
            return _place_quantized(leaf, spec, mesh, path)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params, is_leaf=is_q)


@dataclass(frozen=True)
class ParallelModel:
    """Mesh-placed model.  Build with :func:`make_parallel_model`."""

    cfg: ModelConfig
    mesh: Mesh
    num_microbatches: int = 1
    kv_dtype: str | None = None  # KV-cache dtype override (default cfg.dtype)

    @property
    def num_stages(self) -> int:
        return self.mesh.shape.get("pipe", 1)

    @property
    def pipelined(self) -> bool:
        return self.num_stages > 1

    @property
    def seq_parallel(self) -> bool:
        return self.mesh.shape.get("seq", 1) > 1

    # -- placement ---------------------------------------------------------

    def shard_params(self, params: Params) -> Params:
        """Stage (if pipelined) and place params onto the mesh.

        QuantizedTensor leaves stay quantized-resident on the mesh (SURVEY §7
        hard part 6): data and scale shard under the plain weight's spec,
        with scale blocks refined where a shard boundary would split a block
        (refinement repeats scales to a finer — numerically identical —
        block size).  Leaves whose layout can't shard cleanly replicate,
        loudly, instead of rehydrating the whole tree.
        """
        if self.pipelined:
            params = dict(params)
            params["blocks"] = pipeline_lib.split_stages(params["blocks"], self.num_stages)
            specs = staged_param_specs(self.cfg, self.mesh)
        else:
            specs = specs_lib.param_specs(self.cfg, self.mesh)
        return _place_tree(params, specs, self.mesh)

    def init_cache(
        self, batch: int, max_len: int, prompt_len: int | None = None
    ) -> KVCache:
        cfg = self.cfg
        kvh, hd = cfg.num_kv_heads, cfg.head_dim_
        tp = self.mesh.shape.get("model", 1)
        kv_ax = "model" if kvh % max(tp, 1) == 0 else None
        if self.seq_parallel:
            # Two-region layout for long-context generation: the prompt's KV
            # sharded over 'seq' (each device writes + keeps its own block),
            # the decode region replicated (bounded by max_new_tokens).
            seq_ax = self.mesh.shape["seq"]
            if prompt_len is None:
                raise ValueError(
                    "sequence-parallel KV cache needs prompt_len (the region "
                    "split point); the session path does not support "
                    "seq-parallel decode"
                )
            if prompt_len % seq_ax:
                raise ValueError(
                    f"padded prompt length {prompt_len} not divisible by "
                    f"seq axis {seq_ax}"
                )
            dt = jnp.dtype(self.kv_dtype or cfg.dtype)
            l = cfg.num_layers

            def region(length, spec):
                return jax.lax.with_sharding_constraint(
                    jnp.zeros((l, batch, length, kvh, hd), dt),
                    NamedSharding(self.mesh, spec),
                )

            # k and v must be DISTINCT buffers: callers (runtime/batcher.py)
            # donate the cache, and donating one aliased buffer through two
            # tree leaves is an XLA Execute error.
            return KVCache(
                k=(region(prompt_len, P(None, "data", "seq", kv_ax, None)),
                   region(max_len - prompt_len, P(None, "data", None, kv_ax, None))),
                v=(region(prompt_len, P(None, "data", "seq", kv_ax, None)),
                   region(max_len - prompt_len, P(None, "data", None, kv_ax, None))),
            )
        if self.pipelined:
            p, lp = self.num_stages, cfg.num_layers // self.num_stages
            shape = (p, lp, batch, max_len, kvh, hd)
            spec = P("pipe", None, "data", None, kv_ax, None)
        else:
            shape = (cfg.num_layers, batch, max_len, kvh, hd)
            spec = P(None, "data", None, kv_ax, None)
        sharding = NamedSharding(self.mesh, spec)

        # with_sharding_constraint works both eagerly and under jit (the
        # decode loop allocates its cache inside generate_tokens' trace).
        # k and v are DISTINCT allocations: callers (runtime/batcher.py)
        # donate the cache, and two tree leaves aliasing one buffer is an
        # XLA "donate the same buffer twice" Execute error.
        def z():
            return jax.lax.with_sharding_constraint(
                jnp.zeros(shape, jnp.dtype(self.kv_dtype or cfg.dtype)), sharding
            )

        return KVCache(k=z(), v=z())

    # -- adapters for runtime.generate (hashable bound methods; frozen
    # dataclass => stable hash => jit cache hits across calls) --------------

    def _guard_windowed_decode(self) -> None:
        """Sliding-window mesh decode: the GSPMD and pipelined adapters
        thread the slot->position map the window mask needs for the
        right-padded generate layout (models.model._attention
        key_positions; pipeline_decode derives it per tick), so windowed
        models serve on data/tensor/pipe meshes.  Only the seq-parallel
        cached paths stay guarded: ring/Ulysses attention and the
        two-region seq cache are causal-only and do not carry a window
        bound — decoding there would silently attend past the window."""
        if self.cfg.sliding_window is not None and self.seq_parallel:
            raise ValueError(
                "sequence-parallel decode of sliding_window models is "
                "unsupported (ring/Ulysses attention is causal-only, no "
                "window bound); use a data/model/pipe mesh"
            )

    def as_forward_fn(self):
        self._guard_windowed_decode()
        return self._forward_adapter

    def as_make_cache(self):
        self._guard_windowed_decode()
        return self._make_cache_adapter

    def as_decode_fn(self):
        """Fused wavefront decode loop (pipeline.pipeline_decode) for
        runtime.generate: only meaningful when pipelined."""
        self._guard_windowed_decode()
        return self._decode_adapter if self.pipelined else None

    def _decode_adapter(
        self, params, tok0, prompt_lens, prompt_pad_len, cache, rng,
        max_new_tokens, temperature, top_k, top_p, eos_id, pad_id,
    ):
        toks, _, _ = pipeline_lib.pipeline_decode(
            self.mesh, _local_cfg(self.cfg), params, tok0, prompt_lens,
            prompt_pad_len, cache.k, cache.v, max_new_tokens,
            self.num_microbatches, rng,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_id=eos_id, pad_id=pad_id,
        )
        return toks

    def _forward_adapter(
        self, params, cfg, tokens, positions=None, cache=None,
        cache_index=None, attn_mask=None, key_positions=None,
        kv_tables=None,
    ):
        del cfg  # self.cfg is authoritative
        return self.forward(
            params, tokens, positions=positions, cache=cache,
            cache_index=cache_index, attn_mask=attn_mask,
            key_positions=key_positions, kv_tables=kv_tables,
        )

    def _make_cache_adapter(self, cfg, batch, max_len, prompt_len=None):
        del cfg
        return self.init_cache(batch, max_len, prompt_len=prompt_len)

    # -- execution ---------------------------------------------------------

    @staticmethod
    def _require_native_seq() -> None:
        """The seq-parallel schedules execute only on the jax >= 0.5
        shard_map: under the 0.4.x experimental one (check_rep off, no vma
        types) the compiled ring/merge programs abort XLA:CPU outright —
        a hard process crash, not a failure — so refuse up front.  Abstract
        tracing (tools/graftcheck) goes through ops.ring/ops.ulysses
        directly and stays available on every runtime."""
        if not hasattr(jax, "shard_map"):
            raise RuntimeError(
                "sequence-parallel execution requires jax >= 0.5 "
                "(jax.shard_map); this runtime has only the experimental "
                "shard_map, whose compiled seq schedules crash XLA:CPU"
            )

    def _seq_forward(self, params, tokens, positions, remat):
        """Full forward under shard_map over {'seq'}: sequence axis sharded,
        global positions passed through so RoPE/causality stay correct;
        attention runs the ppermute ring (ops/ring.py) or, when the user set
        attn_impl='ulysses', the all-to-all head scatter (ops/ulysses.py);
        'data'/'model' axes remain GSPMD-auto inside the body."""
        self._require_native_seq()
        cfg = _seq_cfg(self.cfg)
        b, t = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))

        def body(params, tokens, positions):
            logits, _ = model_lib.forward(
                params, cfg, tokens, positions=positions, remat=remat
            )
            return logits

        return jaxcompat.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq", None),
            axis_names={"seq"},
        )(params, tokens, positions)

    def _seq_prefill_cached(self, params, tokens, positions, cache, cache_index, remat):
        """Cached prefill under 'seq': tokens sharded over the sequence,
        each device writes its prefill-region KV block locally."""
        self._require_native_seq()
        cfg = _seq_cfg(self.cfg)
        b, t = tokens.shape
        seq_ax = self.mesh.shape["seq"]
        if t % seq_ax:
            raise ValueError(
                f"prompt length {t} not divisible by seq axis {seq_ax} "
                "(the engine pads prompts to the mesh multiple)"
            )
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
        (pk, dk), (pv, dv) = cache.k, cache.v

        def body(params, tokens, positions, pk, pv, dk, dv):
            logits, new_cache = model_lib.forward(
                params, cfg, tokens, positions=positions,
                cache=KVCache(k=(pk, dk), v=(pv, dv)),
                cache_index=jnp.int32(0), remat=remat,
            )
            (npk, ndk), (npv, ndv) = new_cache.k, new_cache.v
            return logits, npk, npv, ndk, ndv

        seq_kv = P(None, None, "seq", None, None)
        logits, npk, npv, ndk, ndv = jaxcompat.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(), P(None, "seq"), P(None, "seq"), seq_kv, seq_kv, P(), P()),
            out_specs=(P(None, "seq", None), seq_kv, seq_kv, P(), P()),
            axis_names={"seq"},
        )(params, tokens, positions, pk, pv, dk, dv)
        return logits, KVCache(k=(npk, ndk), v=(npv, ndv))

    def _seq_decode_cached(self, params, tokens, positions, cache, cache_index, attn_mask, remat):
        """Single-token decode over the seq-sharded cache: partial softmax
        stats merge across 'seq' with one psum; the query is replicated."""
        self._require_native_seq()
        cfg = _seq_cfg(self.cfg)
        (pk, dk), (pv, dv) = cache.k, cache.v
        t_pref = pk.shape[2]
        if attn_mask is None:
            raise ValueError(
                "seq-parallel cached decode needs the decode loop's explicit "
                "attention mask (runtime.generate supplies it)"
            )
        m = attn_mask[:, 0, 0, :]  # [B, S_total]
        m_pref, m_dec = m[:, :t_pref], m[:, t_pref:]

        def body(params, tokens, positions, pk, pv, dk, dv, m_pref, m_dec, ci):
            logits, new_cache = model_lib.forward(
                params, cfg, tokens, positions=positions,
                cache=KVCache(k=(pk, dk), v=(pv, dv)), cache_index=ci,
                attn_mask=(m_pref, m_dec), remat=remat,
            )
            (npk, ndk), (npv, ndv) = new_cache.k, new_cache.v
            return logits, npk, npv, ndk, ndv

        seq_kv = P(None, None, "seq", None, None)
        logits, npk, npv, ndk, ndv = jaxcompat.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(), P(), P(), seq_kv, seq_kv, P(), P(),
                      P(None, "seq"), P(), P()),
            out_specs=(P(), seq_kv, seq_kv, P(), P()),
            axis_names={"seq"},
        )(params, tokens, positions, pk, pv, dk, dv, m_pref, m_dec, cache_index)
        return logits, KVCache(k=(npk, ndk), v=(npv, ndv))

    def forward(
        self,
        params: Params,
        tokens: jax.Array,
        positions: jax.Array | None = None,
        cache: KVCache | None = None,
        cache_index: jax.Array | None = None,
        attn_mask: jax.Array | None = None,
        remat: bool = False,
        return_aux: bool = False,
        key_positions: jax.Array | None = None,  # [B, S] slot->position map
        #   (sliding-window decode under the right-padded generate layout)
        kv_tables: jax.Array | None = None,  # [B, P] page table — the cache
        #   holds page POOLS sharded over 'model' on KV heads (mesh-native
        #   paged serving; GSPMD path only — the paged decode kernel's
        #   custom_partitioning rule partitions it)
    ) -> tuple[jax.Array, KVCache | None] | tuple[jax.Array, KVCache | None, jax.Array]:
        """Same contract as models.model.forward, but mesh-parallel.
        ``return_aux`` (MoE load-balance loss) flows through on the
        GSPMD paths; the pipeline/seq shard_map schedules return aux=0 —
        train MoE with data/model/expert axes."""
        cfg = self.cfg
        if kv_tables is not None and (self.pipelined or self.seq_parallel):
            raise NotImplementedError(
                "paged decode (kv_tables) runs on pure data/tensor-parallel "
                "meshes only — pipelined/seq-parallel schedules keep "
                "contiguous caches"
            )
        if self.seq_parallel and key_positions is not None:
            raise NotImplementedError(
                "sequence-parallel paths do not thread key_positions "
                "(ring/Ulysses are causal-only)"
            )
        if self.seq_parallel and cache is not None:
            # Long-context *generation* (SURVEY §5.7): prompt KV sharded over
            # 'seq' (two-region cache from init_cache); single-token decode
            # merges partial softmax stats with one psum instead of rotating
            # KV to meet one query.
            if tokens.shape[1] > 1:
                if attn_mask is not None:
                    # Loud, not silently-causal: the sharded prefill cannot
                    # honor an arbitrary mask (ring/Ulysses are causal-only).
                    raise NotImplementedError(
                        "sequence-parallel cached prefill supports causal "
                        "masking only; got an explicit attn_mask"
                    )
                out = self._seq_prefill_cached(
                    params, tokens, positions, cache, cache_index, remat
                )
            else:
                out = self._seq_decode_cached(
                    params, tokens, positions, cache, cache_index, attn_mask, remat
                )
            return (*out, jnp.float32(0.0)) if return_aux else out
        if (
            self.seq_parallel
            and cache is None
            and not self.pipelined
            and attn_mask is None
        ):
            # Long-context no-cache path: sequence sharded over 'seq', ring
            # attention rotates KV blocks over ICI (prefill/training; custom
            # masks fall through to the dense path — causal only).
            logits = self._seq_forward(params, tokens, positions, remat)
            return (logits, None, jnp.float32(0.0)) if return_aux else (logits, None)
        cfg = _local_cfg(cfg)
        if not self.pipelined:
            # GSPMD path: mark the trace so quantized contractions route
            # through the custom_partitioning kernel wrapper (per-shard
            # Pallas tiles + psum over contracted axes — the bandwidth win
            # now applies to plain-TP serving) or, on non-TPU backends /
            # DLT_QUANT_MATMUL_SPMD=0, the dequant+einsum fallback XLA can
            # partition.  A bare pallas_call here would all-gather weights.
            from ..ops.quant_matmul import spmd_fallback

            with spmd_fallback():
                return model_lib.forward(
                    params, cfg, tokens, positions=positions, cache=cache,
                    cache_index=cache_index, remat=remat, attn_mask=attn_mask,
                    return_aux=return_aux, key_positions=key_positions,
                    kv_tables=kv_tables,
                )

        b, t = tokens.shape
        if positions is None:
            base = cache_index if cache_index is not None else 0
            positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32) + base, (b, t))
        x = model_lib.embed(params, cfg, tokens, positions)
        y, new_cache = pipeline_lib.pipeline_blocks(
            self.mesh, cfg, params["blocks"], x, positions,
            num_microbatches=self.num_microbatches,
            cache_k=cache.k if cache is not None else None,
            cache_v=cache.v if cache is not None else None,
            cache_index=cache_index, attn_mask=attn_mask, remat=remat,
            key_positions=key_positions,
        )
        logits = model_lib.unembed(params, cfg, y)
        new = None if cache is None else KVCache(k=new_cache[0], v=new_cache[1])
        return (logits, new, jnp.float32(0.0)) if return_aux else (logits, new)


def _seq_cfg(cfg: ModelConfig) -> ModelConfig:
    """Pick the sequence-parallel attention impl for the shard_map body:
    the user's 'ulysses' is kept, anything else becomes the ring."""
    import dataclasses

    if cfg.attn_impl == "ulysses":
        return cfg
    return dataclasses.replace(cfg, attn_impl="ring")


def _local_cfg(cfg: ModelConfig) -> ModelConfig:
    """Strip sequence-parallel impls for paths that run *outside* shard_map
    (decode-with-cache, pipeline stages): 'ring'/'ulysses' need a bound seq
    axis and would raise; they degrade to the dense dot path."""
    import dataclasses

    if cfg.attn_impl in ("ring", "ulysses"):
        return dataclasses.replace(cfg, attn_impl="dot")
    return cfg


def make_parallel_model(
    cfg: ModelConfig, mesh_cfg: MeshConfig, num_microbatches: int = 1,
    devices: list | None = None, kv_dtype: str | None = None,
) -> ParallelModel:
    from ..core.mesh import build_mesh

    mesh = build_mesh(mesh_cfg, devices)
    if mesh_cfg.pipe > 1 and cfg.num_layers % mesh_cfg.pipe:
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by pipe {mesh_cfg.pipe}"
        )
    if mesh_cfg.pipe > 1 and mesh_cfg.seq > 1:
        # The ring path replaces the pipeline schedule; a seq axis alongside
        # pipe would silently hold inert replicas instead of sharding sequence.
        raise ValueError(
            f"seq={mesh_cfg.seq} cannot combine with pipe={mesh_cfg.pipe}: "
            "ring attention and the pipeline schedule are alternative "
            "shardings of the layer loop — use one, with 'data'/'model' axes"
        )
# NOTE: sliding_window models mesh-TRAIN fine (the cache=None forward
# windows in position space directly); only the mesh DECODE adapters are
# guarded — see ParallelModel._guard_windowed_decode.
    return ParallelModel(
        cfg=cfg, mesh=mesh, num_microbatches=num_microbatches, kv_dtype=kv_dtype
    )
