"""True pipeline parallelism over a `pipe` mesh axis.

The reference's "pipeline" never pipelined: every worker received the same
input and the master collected partials (fan-out/fan-in star,
src/master/node.py:256-269) — activations never flowed worker->worker
(SURVEY §2.3).  Here activations hop stage->stage over ICI via
``lax.ppermute`` inside ``shard_map``:

- stacked block params [L, ...] are reshaped to [P, L/P, ...] and sharded
  over 'pipe' — each device owns a contiguous layer block (stage);
- a GPipe microbatch schedule runs as a ``lax.scan`` over
  ``num_microbatches + P - 1`` ticks; at each tick every stage processes one
  microbatch and the results rotate one stage forward;
- the schedule is a pure scan over ppermute/dynamic-slice ops, so
  ``jax.grad`` differentiates straight through it — the backward pipeline
  schedule falls out of autodiff, no hand-written 1F1B needed;
- the 'model' (tensor-parallel) and 'data' axes stay GSPMD-auto inside the
  body (``axis_names={'pipe'}``), so TP composes with PP without manual
  collectives.

KV-cache decoding: each stage owns the cache slice for its layers
([P, L/P, B, S, KVH, HD] sharded over 'pipe'); at tick t stage s updates the
batch rows of microbatch (t - s), predicated so bubble ticks write no-ops.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core import jaxcompat
from ..core.config import ModelConfig
from ..models import model as model_lib
from ..runtime import sampling

Params = Any


def split_stages(blocks: Params, num_stages: int) -> Params:
    """[L, ...] stacked block params -> [P, L/P, ...]."""
    def r(a):
        l = a.shape[0]
        if l % num_stages:
            raise ValueError(f"layers {l} not divisible by stages {num_stages}")
        return a.reshape(num_stages, l // num_stages, *a.shape[1:])

    return jax.tree.map(r, blocks)


def merge_stages(blocks: Params) -> Params:
    """[P, L/P, ...] -> [L, ...]."""
    return jax.tree.map(lambda a: a.reshape(a.shape[0] * a.shape[1], *a.shape[2:]), blocks)


def _split_mb(x: jax.Array, m: int) -> jax.Array:
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch {b} not divisible by microbatches {m}")
    return x.reshape(m, b // m, *x.shape[1:])


def pipeline_blocks(
    mesh: Mesh,
    cfg: ModelConfig,
    staged_blocks: Params,  # [P, L/P, ...] sharded over 'pipe'
    x: jax.Array,  # [B, T, D] activations after embed
    positions: jax.Array,  # [B, T]
    num_microbatches: int,
    cache_k: jax.Array | None = None,  # [P, L/P, B, S, KVH, HD]
    cache_v: jax.Array | None = None,
    cache_index: jax.Array | None = None,  # scalar int32
    attn_mask: jax.Array | None = None,  # [B, 1, Tq, S]
    remat: bool = False,
    key_positions: jax.Array | None = None,  # [B, S] slot->position map for
    #   sliding-window models under the right-padded decode layout
) -> tuple[jax.Array, tuple[jax.Array, jax.Array] | None]:
    """Run the decoder blocks through the pipeline.  Returns ([B, T, D],
    updated staged caches or None)."""
    num_stages = mesh.shape["pipe"]
    m = num_microbatches
    use_cache = cache_k is not None

    x_mb = _split_mb(x, m)  # [M, mb, T, D]
    pos_mb = _split_mb(positions, m)
    use_mask = attn_mask is not None
    # shard_map wants arrays, not None: dummy when unused (never read).
    mask_mb = (
        _split_mb(attn_mask, m) if use_mask else jnp.zeros((m, 1, 1, 1, 1), dtype=bool)
    )
    use_kpos = key_positions is not None
    kpos_mb = (
        _split_mb(key_positions, m) if use_kpos
        else jnp.zeros((m, 1, 1), dtype=jnp.int32)
    )
    mb_size = x_mb.shape[1]

    def body(staged_blocks, x_mb, pos_mb, cache_k, cache_v, mask_mb, kpos_mb):
        # Per-device views: leading 'pipe' axis has local size 1 -> squeeze.
        blocks = jax.tree.map(lambda a: a[0], staged_blocks)
        stage = jax.lax.axis_index("pipe")
        ck = cache_k[0] if use_cache else None  # [L/P, B, S, KVH, HD]
        cv = cache_v[0] if use_cache else None

        # Mark per-stage buffers as varying over 'pipe' for vma tracking.
        out_mb = jaxcompat.pcast(jnp.zeros_like(x_mb), ("pipe",), to="varying")

        def tick(carry, t):
            state, out_mb, ck, cv = carry
            mb_idx = jnp.clip(t - stage, 0, m - 1)
            valid = jnp.logical_and(t - stage >= 0, t - stage < m)

            x_in = jnp.where(
                stage == 0,
                jax.lax.dynamic_index_in_dim(x_mb, mb_idx, keepdims=False),
                state,
            )
            pos = jax.lax.dynamic_index_in_dim(pos_mb, mb_idx, keepdims=False)
            amask = (
                jax.lax.dynamic_index_in_dim(mask_mb, mb_idx, keepdims=False)
                if use_mask
                else None
            )
            kpos = (
                jax.lax.dynamic_index_in_dim(kpos_mb, mb_idx, keepdims=False)
                if use_kpos
                else None
            )

            if use_cache:
                row0 = mb_idx * mb_size
                ck_mb = jax.lax.dynamic_slice_in_dim(ck, row0, mb_size, axis=1)
                cv_mb = jax.lax.dynamic_slice_in_dim(cv, row0, mb_size, axis=1)
                y, (nk, nv), _ = model_lib.run_blocks(
                    x_in, blocks, cfg, pos, ck_mb, cv_mb, cache_index,
                    remat=remat, attn_mask=amask, key_positions=kpos,
                )
                nk = jnp.where(valid, nk, ck_mb)
                nv = jnp.where(valid, nv, cv_mb)
                ck = jax.lax.dynamic_update_slice_in_dim(ck, nk, row0, axis=1)
                cv = jax.lax.dynamic_update_slice_in_dim(cv, nv, row0, axis=1)
            else:
                # MoE aux loss is not threaded through the pipeline schedule
                # (train MoE with data/tensor/expert axes, not 'pipe').
                y, _, _ = model_lib.run_blocks(
                    x_in, blocks, cfg, pos, None, None, None,
                    remat=remat, attn_mask=amask,
                )

            # Last stage banks its finished microbatch.
            out_idx = jnp.clip(t - (num_stages - 1), 0, m - 1)
            bank = jnp.logical_and(stage == num_stages - 1, t >= num_stages - 1)
            cur = jax.lax.dynamic_index_in_dim(out_mb, out_idx, keepdims=False)
            out_mb = jax.lax.dynamic_update_index_in_dim(
                out_mb, jnp.where(bank, y, cur), out_idx, axis=0
            )

            # Rotate activations one stage forward (circular; stage 0 ignores
            # what it receives and reads the next fresh microbatch instead).
            state = jax.lax.ppermute(
                y, "pipe", [(i, (i + 1) % num_stages) for i in range(num_stages)]
            )
            return (state, out_mb, ck, cv), None

        state0 = jaxcompat.pcast(jnp.zeros_like(x_mb[0]), ("pipe",), to="varying")
        carry = (state0, out_mb, ck, cv)
        (state, out_mb, ck, cv), _ = jax.lax.scan(
            tick, carry, jnp.arange(m + num_stages - 1)
        )
        if use_cache:
            return out_mb[None], ck[None], cv[None]
        return (out_mb[None],)

    in_specs = (
        P("pipe"),  # staged blocks
        P(),        # x_mb (replicated over pipe; data/model axes stay auto)
        P(),        # pos_mb
        P("pipe") if use_cache else P(),
        P("pipe") if use_cache else P(),
        P(),        # mask_mb
        P(),        # kpos_mb
    )
    out_specs = (P("pipe"), P("pipe"), P("pipe")) if use_cache else (P("pipe"),)

    result = jaxcompat.shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        axis_names={"pipe"},
        check_vma=True,
    )(
        staged_blocks, x_mb, pos_mb,
        cache_k if use_cache else jnp.zeros((num_stages, 1)),
        cache_v if use_cache else jnp.zeros((num_stages, 1)),
        mask_mb, kpos_mb,
    )

    if use_cache:
        out_all, new_ck, new_cv = result
    else:
        (out_all,) = result
        new_ck = new_cv = None

    # out_all: [P, M, mb, T, D]; only the last stage's bank is meaningful.
    y = out_all[-1].reshape(x.shape)
    return y, ((new_ck, new_cv) if use_cache else None)


def pipeline_decode(
    mesh: Mesh,
    cfg: ModelConfig,
    params: Params,  # staged tree: params["blocks"] is [P, L/P, ...] over 'pipe'
    tok0: jax.Array,  # [B] int32: first token, sampled from the prefill logits
    prompt_lens: jax.Array,  # [B] int32 true prompt lengths
    prompt_pad_len: int,  # T: padded prompt length = cache write base
    cache_k: jax.Array,  # [P, L/P, B, S, KVH, HD] (prefilled)
    cache_v: jax.Array,
    num_new_tokens: int,
    num_microbatches: int,
    rng: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: int = -1,
    pad_id: int = 0,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused wavefront decode: the whole autoregressive loop as ONE scan, the
    pipeline never drains between tokens (SURVEY §7 hard part 1).

    Running the per-token GPipe schedule once per decode step costs
    ``M + P - 1`` ticks per token and drains the pipeline every step.  Here
    stage 0 starts microbatch ``m``'s token ``j`` at tick ``j*Q + m`` with
    ``Q = max(M, P)``; the last stage's block output rotates (the existing
    circular ppermute) back to stage 0, which applies the final norm +
    unembed, samples token ``j+1``, embeds it, and parks it in a per-
    microbatch buffer until its start tick.  Steady-state cost: ``Q`` ticks
    per token round — with M >= P microbatches in flight every stage is busy
    every tick (zero steady-state bubbles); the per-token schedule can never
    do better than ``M + P - 1``.

    Exactness: identical math to the per-token path under greedy decoding
    (same masks, cache slots, and per-stage block partitioning); under
    sampling the RNG stream differs (keys are ``fold_in(fold_in(rng, j), m)``
    rather than a pre-split array), which is a draw from the same
    distribution.

    Cost note: every stage traces the stage-0 duties (unembed + sample +
    embed) and discards them via ``where`` — SPMD branchless gating.  The
    wasted unembed read per tick is the price of keeping the scan free of
    cross-stage control flow.

    Returns (tokens [B, N] int32 — EOS-frozen rows pad-filled, matching
    runtime.generate semantics — plus the updated staged KV cache halves).
    """
    num_stages = mesh.shape["pipe"]
    p_, m_, n_ = num_stages, num_microbatches, num_new_tokens
    q = max(m_, p_)
    b = tok0.shape[0]
    if b % m_:
        raise ValueError(f"batch {b} not divisible by microbatches {m_}")
    mb = b // m_
    t_base = prompt_pad_len
    s_len = cache_k.shape[3]
    ticks = (n_ - 1) * q + m_ + p_ - 1
    head = {k: v for k, v in params.items() if k != "blocks"}
    head_specs = jax.tree.map(lambda _: P(), head)
    key_data = jax.random.key_data(rng)

    def body(staged_blocks, head, tok0_mb, plens_mb, key_data, cache_k, cache_v):
        blocks = jax.tree.map(lambda a: a[0], staged_blocks)
        ck, cv = cache_k[0], cache_v[0]  # [L/P, B, S, KVH, HD]
        stage = jax.lax.axis_index("pipe")
        base_key = jax.random.wrap_key_data(key_data)
        slots = jnp.arange(s_len, dtype=jnp.int32)
        dtype = jnp.dtype(cfg.dtype)

        def emb(tok, pos):  # [mb] int32, [mb] int32 -> [mb, 1, D]
            return model_lib.embed(head, cfg, tok[:, None], pos[:, None])

        # Stage-0 state (vma-varying; other stages carry discarded copies).
        var = lambda a: jaxcompat.pcast(a, ("pipe",), to="varying")
        buf0 = jnp.stack([emb(tok0_mb[m], plens_mb[m]) for m in range(m_)])
        buf = var(buf0.astype(dtype))  # [M, mb, 1, D] next-token embeds
        done0 = (tok0_mb == eos_id) if eos_id >= 0 else jnp.zeros((m_, mb), bool)
        done = var(done0)
        out = var(jnp.zeros((n_, m_, mb), jnp.int32).at[0].set(tok0_mb))
        state = var(jnp.zeros((mb, 1, buf0.shape[-1]), dtype))

        def tick(carry, t):
            state, buf, done, out, ck, cv = carry

            # -- stage-0 arrival: `state` is what stage P-1 rotated out at
            # the end of tick t-1, i.e. the block output for (m', j') with
            # u' = t - P.  Turn it into token j'+1.
            up = t - p_
            mp = jnp.clip(up % q, 0, m_ - 1)
            jp = up // q
            arr_valid = jnp.logical_and(
                jnp.logical_and(up >= 0, (up % q) < m_), jp + 1 < n_
            )
            logits = model_lib.unembed(head, cfg, state)[:, 0]  # [mb, V] f32
            key = jax.random.fold_in(jax.random.fold_in(base_key, jp + 1), mp)
            tok = sampling.sample(key, logits, temperature, top_k, top_p)
            dmb = jax.lax.dynamic_index_in_dim(done, mp, keepdims=False)
            tok = jnp.where(dmb, jnp.int32(pad_id), tok)
            dnew = jnp.logical_or(dmb, tok == eos_id) if eos_id >= 0 else dmb
            apply = jnp.logical_and(arr_valid, stage == 0)
            done = jax.lax.dynamic_update_index_in_dim(
                done, jnp.where(apply, dnew, dmb), mp, axis=0
            )
            jpc = jnp.clip(jp + 1, 0, n_ - 1)
            cur_out = jax.lax.dynamic_index_in_dim(out, jpc, keepdims=False)
            cur_row = jax.lax.dynamic_index_in_dim(cur_out, mp, keepdims=False)
            new_row = jnp.where(apply, tok, cur_row)
            out = jax.lax.dynamic_update_index_in_dim(
                out,
                jax.lax.dynamic_update_index_in_dim(cur_out, new_row, mp, axis=0),
                jpc, axis=0,
            )
            plens_arr = jax.lax.dynamic_index_in_dim(plens_mb, mp, keepdims=False)
            x_next = emb(tok, plens_arr + jp + 1).astype(dtype)
            cur_buf = jax.lax.dynamic_index_in_dim(buf, mp, keepdims=False)
            buf = jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(apply, x_next, cur_buf), mp, axis=0
            )

            # -- this tick's stage compute: (m, j) with u = t - stage.
            u = t - stage
            m_idx = jnp.clip(u % q, 0, m_ - 1)
            j = jnp.clip(u // q, 0, n_ - 1)
            valid = jnp.logical_and(
                jnp.logical_and(u >= 0, (u % q) < m_), u // q < n_
            )
            x_in = jnp.where(
                stage == 0,
                jax.lax.dynamic_index_in_dim(buf, m_idx, keepdims=False),
                state,
            )
            plens_m = jax.lax.dynamic_index_in_dim(plens_mb, m_idx, keepdims=False)
            pos = (plens_m + j)[:, None]  # [mb, 1]
            prompt_valid = slots[None, :] < plens_m[:, None]
            gen_valid = jnp.logical_and(
                slots[None, :] >= t_base, slots[None, :] <= t_base + j
            )
            mask = jnp.logical_or(prompt_valid, gen_valid)[:, None, None, :]
            row0 = m_idx * mb
            ck_mb = jax.lax.dynamic_slice_in_dim(ck, row0, mb, axis=1)
            cv_mb = jax.lax.dynamic_slice_in_dim(cv, row0, mb, axis=1)
            # Sliding-window models: slot->position map under this layout
            # (prompt slot s holds position s; generated slot t_base + i
            # holds position len + i) — same formula as
            # runtime.generate.window_key_positions, per microbatch.
            kpos = None
            if cfg.sliding_window is not None:
                kpos = jnp.where(
                    slots[None, :] < t_base, slots[None, :],
                    plens_m[:, None] + (slots[None, :] - t_base),
                )
            y, (nk, nv), _ = model_lib.run_blocks(
                x_in, blocks, cfg, pos, ck_mb, cv_mb, t_base + j,
                attn_mask=mask, key_positions=kpos,
            )
            nk = jnp.where(valid, nk, ck_mb)
            nv = jnp.where(valid, nv, cv_mb)
            ck = jax.lax.dynamic_update_slice_in_dim(ck, nk, row0, axis=1)
            cv = jax.lax.dynamic_update_slice_in_dim(cv, nv, row0, axis=1)

            state = jax.lax.ppermute(
                y, "pipe", [(i, (i + 1) % p_) for i in range(p_)]
            )
            return (state, buf, done, out, ck, cv), None

        carry = (state, buf, done, out, ck, cv)
        (state, buf, done, out, ck, cv), _ = jax.lax.scan(
            tick, carry, jnp.arange(ticks)
        )
        return out[None], ck[None], cv[None]

    tok0_mb = tok0.reshape(m_, mb)
    plens_mb = prompt_lens.reshape(m_, mb)
    out_all, new_ck, new_cv = jaxcompat.shard_map(
        body,
        mesh=mesh,
        in_specs=(P("pipe"), head_specs, P(), P(), P(), P("pipe"), P("pipe")),
        out_specs=(P("pipe"), P("pipe"), P("pipe")),
        axis_names={"pipe"},
        check_vma=True,
    )(params["blocks"], head, tok0_mb, plens_mb, key_data, cache_k, cache_v)

    # out_all: [P, N, M, mb]; stage 0 holds the real bank.
    toks = out_all[0].reshape(num_new_tokens, b).T  # [B, N]
    return toks, new_ck, new_cv
