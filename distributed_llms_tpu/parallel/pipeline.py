"""True pipeline parallelism over a `pipe` mesh axis.

The reference's "pipeline" never pipelined: every worker received the same
input and the master collected partials (fan-out/fan-in star,
src/master/node.py:256-269) — activations never flowed worker->worker
(SURVEY §2.3).  Here activations hop stage->stage over ICI via
``lax.ppermute`` inside ``shard_map``:

- stacked block params [L, ...] are reshaped to [P, L/P, ...] and sharded
  over 'pipe' — each device owns a contiguous layer block (stage);
- a GPipe microbatch schedule runs as a ``lax.scan`` over
  ``num_microbatches + P - 1`` ticks; at each tick every stage processes one
  microbatch and the results rotate one stage forward;
- the schedule is a pure scan over ppermute/dynamic-slice ops, so
  ``jax.grad`` differentiates straight through it — the backward pipeline
  schedule falls out of autodiff, no hand-written 1F1B needed;
- the 'model' (tensor-parallel) and 'data' axes stay GSPMD-auto inside the
  body (``axis_names={'pipe'}``), so TP composes with PP without manual
  collectives.

KV-cache decoding: each stage owns the cache slice for its layers
([P, L/P, B, S, KVH, HD] sharded over 'pipe'); at tick t stage s updates the
batch rows of microbatch (t - s), predicated so bubble ticks write no-ops.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.config import ModelConfig
from ..models import model as model_lib

Params = Any


def split_stages(blocks: Params, num_stages: int) -> Params:
    """[L, ...] stacked block params -> [P, L/P, ...]."""
    def r(a):
        l = a.shape[0]
        if l % num_stages:
            raise ValueError(f"layers {l} not divisible by stages {num_stages}")
        return a.reshape(num_stages, l // num_stages, *a.shape[1:])

    return jax.tree.map(r, blocks)


def merge_stages(blocks: Params) -> Params:
    """[P, L/P, ...] -> [L, ...]."""
    return jax.tree.map(lambda a: a.reshape(a.shape[0] * a.shape[1], *a.shape[2:]), blocks)


def _split_mb(x: jax.Array, m: int) -> jax.Array:
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch {b} not divisible by microbatches {m}")
    return x.reshape(m, b // m, *x.shape[1:])


def pipeline_blocks(
    mesh: Mesh,
    cfg: ModelConfig,
    staged_blocks: Params,  # [P, L/P, ...] sharded over 'pipe'
    x: jax.Array,  # [B, T, D] activations after embed
    positions: jax.Array,  # [B, T]
    num_microbatches: int,
    cache_k: jax.Array | None = None,  # [P, L/P, B, S, KVH, HD]
    cache_v: jax.Array | None = None,
    cache_index: jax.Array | None = None,  # scalar int32
    attn_mask: jax.Array | None = None,  # [B, 1, Tq, S]
    remat: bool = False,
) -> tuple[jax.Array, tuple[jax.Array, jax.Array] | None]:
    """Run the decoder blocks through the pipeline.  Returns ([B, T, D],
    updated staged caches or None)."""
    num_stages = mesh.shape["pipe"]
    m = num_microbatches
    use_cache = cache_k is not None

    x_mb = _split_mb(x, m)  # [M, mb, T, D]
    pos_mb = _split_mb(positions, m)
    use_mask = attn_mask is not None
    # shard_map wants arrays, not None: dummy when unused (never read).
    mask_mb = (
        _split_mb(attn_mask, m) if use_mask else jnp.zeros((m, 1, 1, 1, 1), dtype=bool)
    )
    mb_size = x_mb.shape[1]

    def body(staged_blocks, x_mb, pos_mb, cache_k, cache_v, mask_mb):
        # Per-device views: leading 'pipe' axis has local size 1 -> squeeze.
        blocks = jax.tree.map(lambda a: a[0], staged_blocks)
        stage = jax.lax.axis_index("pipe")
        ck = cache_k[0] if use_cache else None  # [L/P, B, S, KVH, HD]
        cv = cache_v[0] if use_cache else None

        # Mark per-stage buffers as varying over 'pipe' for vma tracking.
        out_mb = jax.lax.pcast(jnp.zeros_like(x_mb), ("pipe",), to="varying")

        def tick(carry, t):
            state, out_mb, ck, cv = carry
            mb_idx = jnp.clip(t - stage, 0, m - 1)
            valid = jnp.logical_and(t - stage >= 0, t - stage < m)

            x_in = jnp.where(
                stage == 0,
                jax.lax.dynamic_index_in_dim(x_mb, mb_idx, keepdims=False),
                state,
            )
            pos = jax.lax.dynamic_index_in_dim(pos_mb, mb_idx, keepdims=False)
            amask = (
                jax.lax.dynamic_index_in_dim(mask_mb, mb_idx, keepdims=False)
                if use_mask
                else None
            )

            if use_cache:
                row0 = mb_idx * mb_size
                ck_mb = jax.lax.dynamic_slice_in_dim(ck, row0, mb_size, axis=1)
                cv_mb = jax.lax.dynamic_slice_in_dim(cv, row0, mb_size, axis=1)
                y, (nk, nv), _ = model_lib.run_blocks(
                    x_in, blocks, cfg, pos, ck_mb, cv_mb, cache_index,
                    remat=remat, attn_mask=amask,
                )
                nk = jnp.where(valid, nk, ck_mb)
                nv = jnp.where(valid, nv, cv_mb)
                ck = jax.lax.dynamic_update_slice_in_dim(ck, nk, row0, axis=1)
                cv = jax.lax.dynamic_update_slice_in_dim(cv, nv, row0, axis=1)
            else:
                # MoE aux loss is not threaded through the pipeline schedule
                # (train MoE with data/tensor/expert axes, not 'pipe').
                y, _, _ = model_lib.run_blocks(
                    x_in, blocks, cfg, pos, None, None, None,
                    remat=remat, attn_mask=amask,
                )

            # Last stage banks its finished microbatch.
            out_idx = jnp.clip(t - (num_stages - 1), 0, m - 1)
            bank = jnp.logical_and(stage == num_stages - 1, t >= num_stages - 1)
            cur = jax.lax.dynamic_index_in_dim(out_mb, out_idx, keepdims=False)
            out_mb = jax.lax.dynamic_update_index_in_dim(
                out_mb, jnp.where(bank, y, cur), out_idx, axis=0
            )

            # Rotate activations one stage forward (circular; stage 0 ignores
            # what it receives and reads the next fresh microbatch instead).
            state = jax.lax.ppermute(
                y, "pipe", [(i, (i + 1) % num_stages) for i in range(num_stages)]
            )
            return (state, out_mb, ck, cv), None

        state0 = jax.lax.pcast(jnp.zeros_like(x_mb[0]), ("pipe",), to="varying")
        carry = (state0, out_mb, ck, cv)
        (state, out_mb, ck, cv), _ = jax.lax.scan(
            tick, carry, jnp.arange(m + num_stages - 1)
        )
        if use_cache:
            return out_mb[None], ck[None], cv[None]
        return (out_mb[None],)

    in_specs = (
        P("pipe"),  # staged blocks
        P(),        # x_mb (replicated over pipe; data/model axes stay auto)
        P(),        # pos_mb
        P("pipe") if use_cache else P(),
        P("pipe") if use_cache else P(),
        P(),        # mask_mb
    )
    out_specs = (P("pipe"), P("pipe"), P("pipe")) if use_cache else (P("pipe"),)

    result = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        axis_names={"pipe"},
        check_vma=True,
    )(
        staged_blocks, x_mb, pos_mb,
        cache_k if use_cache else jnp.zeros((num_stages, 1)),
        cache_v if use_cache else jnp.zeros((num_stages, 1)),
        mask_mb,
    )

    if use_cache:
        out_all, new_ck, new_cv = result
    else:
        (out_all,) = result
        new_ck = new_cv = None

    # out_all: [P, M, mb, T, D]; only the last stage's bank is meaningful.
    y = out_all[-1].reshape(x.shape)
    return y, ((new_ck, new_cv) if use_cache else None)
