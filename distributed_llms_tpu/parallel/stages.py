"""Stage assignment: pack layers into pipeline stages.

Keeps the reference's one good algorithm — greedy byte-balanced packing of
whole layers into N shards (src/model/shard_manager.py:44-61) — but fixes its
fatal flaws: the reference packed *non-contiguous* layers (fine for its
fan-out execution, useless for a real pipeline) and its layer-name parsing
matched no real HF checkpoint (defect D6).  Here:

- `partition_contiguous`: optimal contiguous split (DP over prefix sums)
  minimizing the max stage byte size — the policy a `pipe` mesh axis needs;
- `pack_greedy`: the reference's greedy min-bin packing, kept for
  non-pipelined placement (shard-store layout, §checkpoint.store).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class StageAssignment:
    """boundaries[i] = first layer of stage i; stage i owns
    layers[boundaries[i]:boundaries[i+1]]."""

    num_layers: int
    boundaries: tuple[int, ...]  # length num_stages + 1; [0, ..., num_layers]

    @property
    def num_stages(self) -> int:
        return len(self.boundaries) - 1

    def layers_of(self, stage: int) -> range:
        return range(self.boundaries[stage], self.boundaries[stage + 1])

    def stage_of(self, layer: int) -> int:
        return int(np.searchsorted(self.boundaries, layer, side="right") - 1)

    @property
    def uniform(self) -> bool:
        sizes = {len(self.layers_of(s)) for s in range(self.num_stages)}
        return len(sizes) == 1


def partition_contiguous(layer_bytes: list[int], num_stages: int) -> StageAssignment:
    """Optimal contiguous partition minimizing max stage bytes (linear
    partition problem, O(L^2 * S) DP — L is tens of layers, cost trivial)."""
    n = len(layer_bytes)
    if num_stages <= 0 or num_stages > n:
        raise ValueError(f"num_stages {num_stages} must be in [1, {n}]")
    prefix = np.concatenate([[0], np.cumsum(layer_bytes)])

    def seg(i: int, j: int) -> int:  # bytes of layers [i, j)
        return int(prefix[j] - prefix[i])

    INF = float("inf")
    # dp[s][j] = minimal max-stage-cost splitting first j layers into s stages
    dp = np.full((num_stages + 1, n + 1), INF)
    cut = np.zeros((num_stages + 1, n + 1), dtype=int)
    dp[0][0] = 0
    for s in range(1, num_stages + 1):
        for j in range(s, n + 1):
            for i in range(s - 1, j):
                cost = max(dp[s - 1][i], seg(i, j))
                if cost < dp[s][j]:
                    dp[s][j] = cost
                    cut[s][j] = i
    bounds = [n]
    j = n
    for s in range(num_stages, 0, -1):
        j = int(cut[s][j])
        bounds.append(j)
    return StageAssignment(num_layers=n, boundaries=tuple(reversed(bounds)))


def uniform_stages(num_layers: int, num_stages: int) -> StageAssignment:
    """Equal split; requires divisibility (the stacked-param pipeline reshapes
    [L, ...] -> [stages, L/stages, ...])."""
    if num_layers % num_stages:
        raise ValueError(f"{num_layers} layers not divisible by {num_stages} stages")
    per = num_layers // num_stages
    return StageAssignment(
        num_layers=num_layers,
        boundaries=tuple(range(0, num_layers + 1, per)),
    )


def pack_greedy(item_bytes: dict[str, int], num_bins: int) -> dict[str, int]:
    """Greedy largest-first min-bin packing (the reference's algorithm,
    src/model/shard_manager.py:44-61): returns {item: bin}."""
    bins = [0] * num_bins
    out: dict[str, int] = {}
    for name in sorted(item_bytes, key=item_bytes.__getitem__, reverse=True):
        b = int(np.argmin(bins))
        bins[b] += item_bytes[name]
        out[name] = b
    return out
