"""Core transformer layer primitives (pure functions over param pytrees).

Replaces the reference's compute path end-to-end: its "forward" was a
placeholder per-parameter ``torch.matmul`` (src/worker/node.py:24-32) and its
model loading leaned on torch/transformers (src/model/loader.py:5-25).  Here
the decoder blocks are real, written TPU-first:

- params are plain pytrees of jnp arrays, **stacked over the layer axis** so
  layers run under ``lax.scan`` (one trace, XLA-friendly) and pipeline stages
  are contiguous slices of the stacked axis;
- matmuls are einsums in bf16 hitting the MXU; softmax/norms accumulate f32;
- no data-dependent Python control flow — everything jits.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..core.config import ModelConfig

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Norms
# ---------------------------------------------------------------------------

def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    y = x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    return (y * scale.astype(jnp.float32)).astype(dtype)


# ---------------------------------------------------------------------------
# Rotary position embeddings (Llama family)
# ---------------------------------------------------------------------------

def rope_frequencies(
    head_dim: int, theta: float,
    scaling: tuple[float, float, float, int] | None = None,
) -> jax.Array:
    """Inverse frequencies, shape [head_dim // 2], float32.

    ``scaling`` = (factor, low_freq_factor, high_freq_factor,
    original_max_len) applies Llama-3.1's piecewise rescale (HF
    _compute_llama3_parameters): wavelengths beyond
    original_max_len/low_freq_factor divide by ``factor`` (stretched for
    long context), wavelengths under original_max_len/high_freq_factor
    keep their frequency, and the band between interpolates smoothly.
    """
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta**exponent)
    if scaling is not None:
        factor, low, high, old_len = scaling
        wavelen = 2.0 * jnp.pi / inv_freq
        smooth = (old_len / wavelen - low) / (high - low)
        smooth = jnp.clip(smooth, 0.0, 1.0)  # 0 = fully scaled, 1 = kept
        inv_freq = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    return inv_freq


def apply_rope(
    x: jax.Array, positions: jax.Array, theta: float,
    scaling: tuple[float, float, float, int] | None = None,
) -> jax.Array:
    """Rotate half-pairs.  x: [B, T, H, D]; positions: [B, T] int32.

    Uses the HF/Llama convention: the head dim is split into two halves
    (x1 = x[..., :D/2], x2 = x[..., D/2:]) rotated jointly — matches the
    checkpoint layout our converter targets.  ``scaling`` is the Llama-3.1
    frequency rescale (see rope_frequencies).
    """
    half = x.shape[-1] // 2
    freqs = rope_frequencies(x.shape[-1], theta, scaling)  # [half]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, half]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, T, 1, half]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    x1f = x1.astype(jnp.float32)
    x2f = x2.astype(jnp.float32)
    out = jnp.concatenate([x1f * cos - x2f * sin, x2f * cos + x1f * sin], axis=-1)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# Attention
# ---------------------------------------------------------------------------

def repeat_kv(x: jax.Array, q_per_kv: int) -> jax.Array:
    """[B, S, KVH, D] -> [B, S, KVH*q_per_kv, D] for grouped-query attention."""
    if q_per_kv == 1:
        return x
    b, s, kvh, d = x.shape
    x = jnp.broadcast_to(x[:, :, :, None, :], (b, s, kvh, q_per_kv, d))
    return x.reshape(b, s, kvh * q_per_kv, d)


def dot_product_attention(
    q: jax.Array,  # [B, Tq, H, D]
    k: jax.Array,  # [B, Tk, H, D]
    v: jax.Array,  # [B, Tk, H, D]
    mask: jax.Array | None,  # broadcastable to [B, H, Tq, Tk]; True = attend
) -> jax.Array:
    """Softmax(QK^T)V with f32 accumulation.  XLA fuses this into MXU-friendly
    batched matmuls; the Pallas flash kernel in ops/ is the drop-in for long
    sequences."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


def causal_mask(
    q_positions: jax.Array,
    k_positions: jax.Array,
    k_valid: jax.Array | None = None,
    window: int | None = None,
) -> jax.Array:
    """Boolean mask [B, 1, Tq, Tk]: query at position p attends keys at
    positions <= p.  ``k_valid`` ([B, Tk] bool) masks unwritten cache slots.
    ``window`` (Mistral sliding-window attention) further restricts keys to
    positions in (p - window, p]."""
    mask = k_positions[:, None, None, :] <= q_positions[:, None, :, None]
    if k_valid is not None:
        mask = jnp.logical_and(mask, k_valid[:, None, None, :])
    if window is not None:
        mask = and_window(mask, q_positions, k_positions, window)
    return mask


def and_window(
    mask: jax.Array,
    q_positions: jax.Array,
    k_positions: jax.Array,
    window: int,
) -> jax.Array:
    """AND the sliding-window lower bound (keys in (p - window, p]) into an
    existing attention mask — the single definition of the window semantics,
    shared by causal_mask and the caller-supplied-mask paths in
    models.model._attention."""
    return jnp.logical_and(
        mask,
        k_positions[:, None, None, :] > q_positions[:, None, :, None] - window,
    )


# ---------------------------------------------------------------------------
# Projections (einsum conventions shared by all families)
# ---------------------------------------------------------------------------

def _is_quantized(w: Any) -> bool:
    # Duck-typed (bits/scale/data) to keep layers import-light; the leaf type
    # is checkpoint.quantize.QuantizedTensor.
    return hasattr(w, "bits") and hasattr(w, "scale") and hasattr(w, "data")


def _contract(x: jax.Array, w: Any, eq: str, k_lead: int) -> jax.Array:
    """einsum for plain weights; fused dequant-matmul (ops/quant_matmul) for
    QuantizedTensor weights under weight-only quantized serving."""
    if _is_quantized(w):
        from ..ops.quant_matmul import quant_contract

        return quant_contract(x, w, k_lead, eq)
    return jnp.einsum(eq, x, w)


def _plain(b: Any) -> jax.Array:
    """Rehydrate a (rare, legacy-store) quantized bias/vector leaf."""
    if _is_quantized(b):
        from ..checkpoint.quantize import dequantize

        return dequantize(b)
    return b


def qkv_project(x: jax.Array, p: Params, cfg: ModelConfig) -> tuple[jax.Array, jax.Array, jax.Array]:
    """x: [B, T, D] -> q [B, T, H, hd], k/v [B, T, KVH, hd].

    Weight layout: wq [D, H, hd], wk/wv [D, KVH, hd] — head axis explicit so
    tensor-parallel sharding annotates the head dim directly.
    """
    q = _contract(x, p["wq"], "btd,dhk->bthk", 1)
    k = _contract(x, p["wk"], "btd,dhk->bthk", 1)
    v = _contract(x, p["wv"], "btd,dhk->bthk", 1)
    if "bq" in p:
        q = q + _plain(p["bq"])
        k = k + _plain(p["bk"])
        v = v + _plain(p["bv"])
    return q, k, v


def out_project(x: jax.Array, p: Params) -> jax.Array:
    """x: [B, T, H, hd] -> [B, T, D].  wo: [H, hd, D]."""
    out = _contract(x, p["wo"], "bthk,hkd->btd", 2)
    if "bo" in p:
        out = out + _plain(p["bo"])
    return out


def mlp_gelu(x: jax.Array, p: Params, activation: str = "gelu") -> jax.Array:
    """GPT-2-layout MLP: act(x W_in + b) W_out + b.  ``activation``:
    "relu" (OPT), "gelu_exact" (erf gelu — HF's "gelu"), anything else the
    tanh approximation (HF's "gelu_new", GPT-2's convention)."""
    h = _contract(x, p["w_in"], "btd,df->btf", 1) + _plain(p["b_in"])
    if activation == "relu":
        h = jax.nn.relu(h)
    elif activation == "gelu_exact":
        h = jax.nn.gelu(h, approximate=False)
    elif activation in ("gelu", "gelu_new"):
        h = jax.nn.gelu(h, approximate=True)
    else:  # loud, not silently-gelu: wrong activation = wrong logits
        raise ValueError(f"unsupported MLP activation {activation!r}")
    return _contract(h, p["w_out"], "btf,fd->btd", 1) + _plain(p["b_out"])


def mlp_swiglu(x: jax.Array, p: Params, gate_act: str = "silu") -> jax.Array:
    """Gated MLP: (act(x W_gate) * (x W_up)) W_down, no biases.
    ``gate_act``: "silu" (Llama/Qwen2) or "gelu_tanh" (Gemma's GeGLU)."""
    gate = _contract(x, p["w_gate"], "btd,df->btf", 1)
    up = _contract(x, p["w_up"], "btd,df->btf", 1)
    act = (
        jax.nn.silu if gate_act == "silu"
        else lambda g: jax.nn.gelu(g, approximate=True)
    )
    h = act(gate) * up
    return _contract(h, p["w_down"], "btf,fd->btd", 1)


def moe_swiglu(
    x: jax.Array, p: Params, cfg: ModelConfig
) -> tuple[jax.Array, jax.Array]:
    """Mixture-of-experts SwiGLU MLP (Mixtral-style routing, GShard-style
    capacity semantics, scatter-based dispatch).  Net-new vs the reference
    (SURVEY §2.3: MoE absent).  Returns (output, aux_load_balance_loss).

    - router: top-k experts per token, gates = softmax over the k logits
      (Mixtral convention);
    - dispatch: every (token, choice) claim computes its slot index
      ``expert * cap + position_in_expert`` and the token rows are
      scatter-added into a per-expert buffer [E, C, D] — O(tokens·D) memory,
      not the O(tokens²) of dense one-hot dispatch tensors.  C =
      ceil(capacity_factor · k · tokens / E); earlier-ranked choices win
      capacity first, overflow claims are dropped (contribute zero) — all
      shapes static, XLA-friendly;
    - expert compute: per-expert SwiGLU over stacked weights [E, D, F]; with
      the expert axis sharded over the 'expert' mesh axis, GSPMD turns the
      scatter/gather into the all-to-alls of expert parallelism;
    - aux loss: Switch-Transformer load-balancing term
      ``E · Σ_e importance_e · load_e`` (mean router prob × dispatched
      fraction) — scale by ``cfg.moe_aux_loss_weight`` and add to the task
      loss, or the router collapses and capacity silently drops most tokens.

    p: router [D, E], w_gate/w_up [E, D, F], w_down [E, F, D].

    Quantized-resident expert weights rehydrate here (per layer, inside the
    scan): the fused kernel targets 2D contractions, not the batched
    per-expert einsums below.
    """
    if any(_is_quantized(w) for w in p.values()):
        from ..checkpoint.quantize import dequantize_tree

        p = dequantize_tree(p, x.dtype)
    b, t, d = x.shape
    e, k = cfg.num_experts, cfg.num_experts_per_token
    s = b * t
    cap = max(1, int(-(-cfg.moe_capacity_factor * k * s // e)))  # ceil
    xf = x.reshape(s, d)

    logits = jnp.einsum(
        "sd,de->se", xf, p["router"], preferred_element_type=jnp.float32
    )
    topv, topi = jax.lax.top_k(logits, k)  # [s, k]
    gates = jax.nn.softmax(topv, axis=-1)  # [s, k] f32

    # Choice-major claim order: every token's 1st choice claims capacity
    # before any 2nd choice does.  eid: [k*s] expert id per claim.
    eid = topi.T.reshape(k * s)
    oh = jax.nn.one_hot(eid, e, dtype=jnp.float32)  # [k*s, e]
    pos = jnp.sum((jnp.cumsum(oh, axis=0) - 1.0) * oh, axis=-1)  # [k*s]
    keep = pos < cap
    slot = jnp.where(keep, eid * cap + pos.astype(jnp.int32), e * cap)

    token_idx = jnp.tile(jnp.arange(s), k)  # claim -> source token
    buf = jnp.zeros((e * cap + 1, d), xf.dtype)  # +1: overflow dump row
    buf = buf.at[slot].add(xf[token_idx] * keep[:, None].astype(xf.dtype))
    xe = buf[:-1].reshape(e, cap, d)

    g = jnp.einsum("ecd,edf->ecf", xe, p["w_gate"])
    u = jnp.einsum("ecd,edf->ecf", xe, p["w_up"])
    ye = jnp.einsum("ecf,efd->ecd", jax.nn.silu(g) * u, p["w_down"])

    yflat = jnp.concatenate([ye.reshape(e * cap, d), jnp.zeros((1, d), ye.dtype)])
    gathered = yflat[slot]  # [k*s, d]; dropped claims hit the zero row
    w = (gates.T.reshape(k * s) * keep).astype(gathered.dtype)
    y = jnp.sum((gathered * w[:, None]).reshape(k, s, d), axis=0)

    # Switch-style load-balance aux: importance (mean prob) x load (dispatch
    # fraction) per expert, scaled by E so the balanced value is ~1.
    probs = jax.nn.softmax(logits, axis=-1)  # [s, e] f32
    importance = jnp.mean(probs, axis=0)
    load = jnp.sum(oh * keep[:, None].astype(oh.dtype), axis=0) / (s * k)
    aux = e * jnp.sum(importance * load)
    return y.reshape(b, t, d).astype(x.dtype), aux
