"""Decoder-only transformer forward pass (GPT-2 and Llama families).

Pure functions over stacked-layer param pytrees; covers the role of the
reference's model layer (src/model/loader.py, src/worker/node.py:13-32) with a
*real* transformer forward — the reference's compute was a placeholder matmul
(src/worker/node.py:24-32) and no decode loop existed anywhere (SURVEY §2.5).

Layout conventions:
- params["blocks"][...] arrays have a leading layer axis L; blocks execute
  under ``lax.scan`` so XLA traces one block and reuses it L times.
- KV cache is a preallocated [L, B, S, KVH, HD] pair living in HBM, updated
  with ``dynamic_update_slice`` at jit-static shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from ..core import jaxcompat
from ..core.config import ModelConfig
from . import layers
from .layers import Params


# ---------------------------------------------------------------------------
# KV cache
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclass
class KVCache:
    """Preallocated per-layer KV cache, [L, B, S, KVH, HD].

    Under sequence parallelism ``k``/``v`` are two-region tuples
    ``(prefill, decode)`` instead (see models.model._seq_cached_attention);
    every consumer treats the fields as opaque pytrees."""

    k: Any
    v: Any

    @property
    def max_len(self) -> int:
        if isinstance(self.k, tuple):  # seq-parallel two-region layout
            return self.k[0].shape[2] + self.k[1].shape[2]
        return self.k.shape[2]


@dataclass
class QuantKVCache:
    """Int8-quantized KV page pool (``--kv-bits 8`` tiering): ``k``/``v``
    hold the pool pages at int8 ([L, NB, BLK, KVH, HD]) and
    ``k_scale``/``v_scale`` one float32 absmax scale per head-dim vector
    ([L, NB, BLK, KVH] — checkpoint.quantize.kv_quantize's layout).  Pages
    are quantized ONCE at the write (admission splice / decode-step
    scatter) and dequantized inside the attention read (the decode
    kernel's int8 leg folds the scales into the contraction), so pool
    storage is never materialized full-width.  ``row_dtype`` names the
    dequantized dtype transient row caches (and gathers) restore to —
    static metadata, so jit keys stay stable.

    Decode-only through :func:`forward` (requires ``kv_tables``): the
    contiguous per-row and prefill paths keep full-width caches."""

    k: Any
    v: Any
    k_scale: Any
    v_scale: Any
    row_dtype: str = "bfloat16"


# data/scales are pytree children; row_dtype is static metadata (hashable,
# part of the jit key — exactly how QuantizedTensor registers its bits).
jax.tree_util.register_dataclass(
    QuantKVCache,
    data_fields=["k", "v", "k_scale", "v_scale"],
    meta_fields=["row_dtype"],
)


def init_cache(
    cfg: ModelConfig, batch: int, max_len: int, dtype: Any = None,
    prompt_len: int | None = None,
) -> KVCache:
    """``prompt_len`` is part of the shared make_cache protocol (the
    seq-parallel cache splits regions there); the dense layout ignores it."""
    del prompt_len
    dtype = dtype or jnp.dtype(cfg.dtype)
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim_)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------

def _paged_window_attention(q, k, v, p, layer_cache, cache_index, kv_tables):
    """T-token paged attention window (T >= 2, static): the speculative
    draft/verify pass against a page-pool cache.  K/V for all T tokens
    scatter through the page table first (slots cache_index..+T-1 — the
    caller's growth loop guaranteed pages cover the window), then each
    query offset j reads its row's prefix through slot cache_index+j via
    the paged decode kernel.  Freed rows' tables are zeroed to the shared
    scratch page, so duplicate (page, off) scatter targets are possible
    and tolerated exactly as in the single-token leg (XLA picks a winner;
    no live row reads the scratch page).  An int8 pool (4-tuple
    layer_cache) quantizes the whole window once at the write and hands
    the kernel the scales — pool reads stay 1 byte/elem."""
    from ..ops import decode_attn

    t_w = q.shape[1]
    rows = jnp.arange(q.shape[0], dtype=jnp.int32)
    quant = len(layer_cache) == 4
    blk = layer_cache[0].shape[1]
    idx = cache_index[:, None] + jnp.arange(t_w, dtype=jnp.int32)[None, :]
    page = kv_tables[rows[:, None], idx // blk]  # [B, T]
    off = idx % blk
    if quant:
        from ..checkpoint.quantize import kv_quantize

        ck, cv, sk, sv = layer_cache
        kq, ks = kv_quantize(k)  # [B, T, KVH, HD] i8, [B, T, KVH] f32
        vq, vs = kv_quantize(v)
        ck = ck.at[page, off].set(kq)
        cv = cv.at[page, off].set(vq)
        sk = sk.at[page, off].set(ks)
        sv = sv.at[page, off].set(vs)
        new_cache = (ck, cv, sk, sv)
        scales = {"k_scale": sk, "v_scale": sv}
    else:
        ck, cv = layer_cache
        ck = ck.at[page, off].set(k.astype(ck.dtype))
        cv = cv.at[page, off].set(v.astype(cv.dtype))
        new_cache = (ck, cv)
        scales = {}
    out = jnp.concatenate(
        [
            decode_attn.paged_decode_attention(
                q[:, j: j + 1], ck, cv, cache_index + 1 + j, kv_tables,
                **scales,
            )
            for j in range(t_w)
        ],
        axis=1,
    )
    return layers.out_project(out, p), new_cache


def _attention(
    x: jax.Array,
    p: Params,
    cfg: ModelConfig,
    positions: jax.Array,
    layer_cache: tuple[jax.Array, jax.Array] | None,
    cache_index: jax.Array | None,
    use_rope: bool,
    attn_mask: jax.Array | None = None,  # broadcastable to [B, H, Tq, S]
    std_layout: bool = False,  # positions are the standard arange (forward
    #                            generated them itself) — unlocks the flash
    #                            kernel's static-causal fast path
    kv_tables: jax.Array | None = None,  # [B, P] int32 page table: the
    #                            layer cache is a PAGE POOL [NB, BLK, KVH,
    #                            HD] and row b's slot s lives at
    #                            (tables[b, s//BLK], s%BLK).  Decode-only
    #                            (T == 1, per-row cache_index); the mask is
    #                            implicitly the prefix [0, cache_index[b]].
    key_positions: jax.Array | None = None,  # [B, S] true RoPE position of
    #                            each cache slot — ONLY consulted by the
    #                            sliding-window mask.  Contiguous layouts
    #                            (slot == position: the continuous batcher)
    #                            leave it None; gapped layouts MUST pass it
    #                            or the window silently widens by the pad
    #                            amount on generated keys.  Gapped = the
    #                            right-padded generate/speculative layout
    #                            (prompt slots 0..T-1, generated token j at
    #                            slot T+j but position len+j) AND multi-turn
    #                            sessions (session_step carries the map as
    #                            Session.slot_positions state).
) -> tuple[jax.Array, tuple[jax.Array, jax.Array] | None]:
    q, k, v = layers.qkv_project(x, p, cfg)
    if use_rope:
        rope_scale = (
            (cfg.rope_scaling_factor, cfg.rope_low_freq_factor,
             cfg.rope_high_freq_factor, cfg.rope_original_max_len)
            if cfg.rope_scaling_factor != 1.0 else None  # Llama-3.1 rescale
        )
        if cfg.rotary_pct < 1.0:
            # Partial rotary (GPT-NeoX/Pythia): only the first rotary_pct
            # of each head's dims rotate; the rest are position-free.
            rot = int(cfg.head_dim_ * cfg.rotary_pct)

            def _rope(t):
                return jnp.concatenate(
                    [layers.apply_rope(t[..., :rot], positions,
                                       cfg.rope_theta, rope_scale),
                     t[..., rot:]], axis=-1,
                )

            q, k = _rope(q), _rope(k)
        else:
            q = layers.apply_rope(q, positions, cfg.rope_theta, rope_scale)
            k = layers.apply_rope(k, positions, cfg.rope_theta, rope_scale)

    if kv_tables is not None:
        if layer_cache is None or getattr(cache_index, "ndim", 0) != 1:
            raise ValueError(
                "paged attention is per-row decode (a per-row cache_index "
                "over a page-pool cache)"
            )
        if cfg.sliding_window is not None:
            raise ValueError(
                "paged decode attends each row's full cache prefix; it "
                "cannot honor sliding_window"
            )
        from ..ops import decode_attn

        if x.shape[1] > 1:
            # Multi-token paged WINDOW (the speculative verify pass): row
            # b's T tokens scatter their K/V through the page table at
            # slots cache_index[b]..cache_index[b]+T-1, and query j reads
            # its row's prefix through slot cache_index[b]+j — per-offset
            # lengths give exact causality inside the window while the
            # paged kernel's prefix contract covers everything before it.
            # T is static (spec_k + 1), so the per-offset reads unroll
            # into T kernel calls inside ONE compiled program; the MXU
            # still sees the (k+1)-token matmuls everywhere else in the
            # block, which is the point of verification.  Rollback is
            # free, exactly like the contiguous spec cache: slots past
            # the committed frontier hold junk no read ever admits
            # (lengths cap every read), awaiting overwrite.
            return _paged_window_attention(
                q, k, v, p, layer_cache, cache_index, kv_tables
            )

        if len(layer_cache) == 4:
            # Int8-quantized pool (QuantKVCache per layer): quantize this
            # step's single K/V vector per (row, head) with the absmax
            # scale machinery (checkpoint.quantize.kv_quantize), scatter
            # int8 data + f32 scale, and hand the kernel the scales — the
            # int8 leg folds them into the attention contraction, so the
            # pool is read at 1 byte/elem and never dequantized in HBM.
            from ..checkpoint.quantize import kv_quantize

            ck, cv, sk, sv = layer_cache
            blk = ck.shape[1]
            rows = jnp.arange(x.shape[0], dtype=jnp.int32)
            page = kv_tables[rows, cache_index // blk]
            off = cache_index % blk
            kq, ks = kv_quantize(k[:, 0])  # [B, KVH, HD] i8, [B, KVH] f32
            vq, vs = kv_quantize(v[:, 0])
            # Same duplicate-tolerant scatter contract as the full-width
            # branch below (freed rows share the scratch page).
            ck = ck.at[page, off].set(kq)
            cv = cv.at[page, off].set(vq)
            sk = sk.at[page, off].set(ks)
            sv = sv.at[page, off].set(vs)
            out = decode_attn.paged_decode_attention(
                q, ck, cv, cache_index + 1, kv_tables,
                k_scale=sk, v_scale=sv,
            )
            return layers.out_project(out, p), (ck, cv, sk, sv)

        ck, cv = layer_cache  # [NB, BLK, KVH, HD] page pools
        blk = ck.shape[1]
        rows = jnp.arange(x.shape[0], dtype=jnp.int32)
        page = kv_tables[rows, cache_index // blk]
        off = cache_index % blk
        # Per-row single-slot write into each row's current page.  LIVE
        # rows own distinct pages, but FREED rows' tables are zeroed to the
        # shared scratch page, so two inactive rows CAN produce identical
        # (page, off) indices — the scatter must tolerate duplicates (XLA
        # picks a winner; the scratch page is never read by a live row).
        # Do NOT add unique_indices=True here.
        ck = ck.at[page, off].set(k[:, 0].astype(ck.dtype))
        cv = cv.at[page, off].set(v[:, 0].astype(cv.dtype))
        out = decode_attn.paged_decode_attention(
            q, ck, cv, cache_index + 1, kv_tables
        )
        return layers.out_project(out, p), (ck, cv)

    if (
        cfg.attn_impl == "flash"
        and attn_mask is None
        and layer_cache is None
    ):
        # Self-attention over the input block (training / no-cache eval).
        # Sliding-window models ride the kernel's window band (positions
        # space, layers.and_window semantics): out-of-window tiles are
        # skipped without even a DMA, so windowed prefill work scales with
        # the window instead of the sequence.
        from ..ops import flash

        out = flash.flash_attention(
            q, k, v,
            q_positions=None if std_layout else positions,
            k_positions=None if std_layout else positions,
            causal=True, window=cfg.sliding_window,
        )
        return layers.out_project(out, p), None

    if cfg.attn_impl in ("ring", "ulysses") and layer_cache is not None:
        # Sequence-parallel cached generation (SURVEY §5.7): the KV cache is
        # split into a seq-sharded prefill region and a small replicated
        # decode region (parallel.api builds it; see ParallelModel.init_cache).
        return _seq_cached_attention(
            q, k, v, p, cfg, positions, layer_cache, cache_index, attn_mask
        )

    if cfg.attn_impl in ("ring", "ulysses") and layer_cache is None:
        # Sequence-parallel paths: we are inside a shard_map over the 'seq'
        # mesh axis (ParallelModel handles the wrapping); positions carry
        # *global* indices so causality holds across blocks.
        if attn_mask is not None:
            raise NotImplementedError(
                f"{cfg.attn_impl} attention supports causal masking only"
            )
        if cfg.attn_impl == "ring":
            from ..ops import ring

            out = ring.ring_attention(q, k, v, positions, positions, axis_name="seq")
        else:
            from ..ops import ulysses

            out = ulysses.ulysses_attention(q, k, v, positions, axis_name="seq")
        return layers.out_project(out, p), None

    if layer_cache is not None:
        ck, cv = layer_cache  # [B, S, KVH, HD]
        if getattr(cache_index, "ndim", 0) == 1:
            # Per-ROW write slots (continuous batching: rows admitted at
            # different times sit at different depths).  Only the KV write
            # scatters; everything else stays batched.  Callers must supply
            # attn_mask — the shared k_valid derivation below assumes one
            # scalar frontier.
            if attn_mask is None:
                raise ValueError(
                    "per-row cache_index requires an explicit attn_mask"
                )
            row_upd = jax.vmap(
                lambda c, u, i: jax.lax.dynamic_update_slice(c, u, (i, 0, 0))
            )
            ck = row_upd(ck, k.astype(ck.dtype), cache_index)
            cv = row_upd(cv, v.astype(cv.dtype), cache_index)
            if cfg.ragged_decode and x.shape[1] == 1:
                # Ragged read: row b touches only [0, cache_index[b]] of the
                # cache (lengths = cache_index + 1 includes the slot just
                # written above).  cfg.ragged_decode is the caller's promise
                # that attn_mask IS that prefix mask (core/config.py).
                # Sliding-window models pass the window through: the kernel
                # reads only [length - window, length) per row — exact
                # because the ragged contract layout is slot == position.
                from ..ops import decode_attn

                # ck/cv go in at the CACHE's dtype — the kernel casts per
                # block in VMEM, so a kv_dtype != compute dtype never costs
                # a full-width HBM copy of the cache.
                out = decode_attn.ragged_decode_attention(
                    q, ck, cv, cache_index + 1, window=cfg.sliding_window,
                )
                return layers.out_project(out, p), (ck, cv)
        else:
            ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, cache_index, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, cache_index, 0, 0))
        if attn_mask is None:
            s = ck.shape[1]
            k_positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (x.shape[0], s))
            k_valid = k_positions < (cache_index + x.shape[1])
            if (cfg.attn_impl == "flash" and x.shape[1] > 1
                    and (cfg.sliding_window is None or key_positions is None)):
                # Prefill into a (longer, padded) cache: the flash kernel
                # masks the unwritten tail instead of computing a dense
                # [Tq, max_len] score matrix.  Single-token decode stays on
                # the dense path (the kernel targets block-sized Tq).
                # Windowed models ride the kernel's window band here too —
                # the kernel's single k_positions vector drives causality
                # AND the window, which is exact precisely when slot ==
                # position for written slots (attn_mask is None and no
                # key_positions map => the ungapped prefill layout); gapped
                # layouts supply key_positions and take the dense window
                # path below.
                from ..ops import flash

                out = flash.flash_attention(
                    q, ck.astype(q.dtype), cv.astype(q.dtype),
                    q_positions=positions, k_positions=k_positions,
                    k_valid=k_valid, causal=True, window=cfg.sliding_window,
                )
                return layers.out_project(out, p), (ck, cv)
            # Causality/validity compare SLOT indices (the write frontier);
            # the window compares POSITIONS — for gapped layouts the caller
            # supplies key_positions (see the parameter comment above).
            attn_mask = layers.causal_mask(positions, k_positions, k_valid)
            if cfg.sliding_window is not None:
                kpos = k_positions if key_positions is None else key_positions
                attn_mask = layers.and_window(
                    attn_mask, positions, kpos, cfg.sliding_window
                )
        elif cfg.sliding_window is not None:
            # Caller-supplied masks (continuous batching's per-row prefix
            # masks, padded prefill) carry causality/validity but not the
            # window — AND it in here so no dense cached path can silently
            # attend past the window.
            if key_positions is None:
                s = ck.shape[1]
                key_positions = jnp.broadcast_to(
                    jnp.arange(s, dtype=jnp.int32), (x.shape[0], s)
                )
            attn_mask = layers.and_window(
                attn_mask, positions, key_positions, cfg.sliding_window
            )
        k_full = layers.repeat_kv(ck.astype(q.dtype), cfg.q_per_kv)
        v_full = layers.repeat_kv(cv.astype(q.dtype), cfg.q_per_kv)
        out = layers.dot_product_attention(q, k_full, v_full, attn_mask)
        new_cache = (ck, cv)
    else:
        if attn_mask is None:
            mask = layers.causal_mask(positions, positions, window=cfg.sliding_window)
        else:
            mask = attn_mask
            if cfg.sliding_window is not None:
                mask = layers.and_window(
                    mask, positions, positions, cfg.sliding_window
                )
        k_full = layers.repeat_kv(k, cfg.q_per_kv)
        v_full = layers.repeat_kv(v, cfg.q_per_kv)
        out = layers.dot_product_attention(q, k_full, v_full, mask)
        new_cache = None
    return layers.out_project(out, p), new_cache


def _seq_cached_attention(
    q: jax.Array,  # [B, Tq, H, HD] (post-RoPE)
    k: jax.Array,  # [B, Tq, KVH, HD]
    v: jax.Array,
    p: Params,
    cfg: ModelConfig,
    positions: jax.Array,
    layer_cache: tuple,  # ((ck_pref, ck_dec), (cv_pref, cv_dec))
    cache_index: jax.Array,
    attn_mask,
) -> tuple[jax.Array, tuple]:
    """Cached attention under sequence parallelism — runs inside a shard_map
    over the 'seq' axis (parallel.api wraps it).

    Two-region cache layout: the prefill region holds the long prompt's KV
    sharded over 'seq' (each device keeps its own block — written locally,
    never moved); the decode region holds generated tokens' KV replicated
    (bounded by max_new_tokens, a sliver next to a long-context prompt).

    Prefill (Tq > 1): this device's block fills its prefill slice wholesale
    and attention is the ring / Ulysses pass.  Decode (Tq == 1): the token's
    KV appends to the decode region on every device, and attention merges
    flash-style partial stats across the seq axis (one psum) — the KV stays
    put instead of rotating to meet a single query (ops/ring.py,
    seq_cached_decode_attention)."""
    from ..ops import ring

    (ck_pref, ck_dec), (cv_pref, cv_dec) = layer_cache
    tq = q.shape[1]
    if tq > 1:
        # -- prefill: whole (sharded) prompt in one pass at cache_index 0.
        if attn_mask is not None:
            raise NotImplementedError(
                "sequence-parallel prefill supports causal masking only"
            )
        if tq != ck_pref.shape[1]:
            raise ValueError(
                f"seq-parallel prefill expects the full prompt at once: got "
                f"{tq} local tokens for a {ck_pref.shape[1]}-slot local "
                "prefill region (chunked prefill is unsupported here)"
            )
        ck_pref = k.astype(ck_pref.dtype)
        cv_pref = v.astype(cv_pref.dtype)
        if cfg.attn_impl == "ring":
            out = ring.ring_attention(q, k, v, positions, positions, axis_name="seq")
        else:
            from ..ops import ulysses

            out = ulysses.ulysses_attention(q, k, v, positions, axis_name="seq")
        return layers.out_project(out, p), ((ck_pref, ck_dec), (cv_pref, cv_dec))

    # -- decode: append this token's KV to the replicated decode region.
    if not isinstance(attn_mask, tuple):
        raise ValueError(
            "seq-parallel cached decode needs attn_mask=(prefill_mask, "
            "decode_mask) — ParallelModel.forward splits the global mask"
        )
    t_pref_global = ck_pref.shape[1] * jaxcompat.axis_size("seq")
    di = cache_index - t_pref_global
    ck_dec = jax.lax.dynamic_update_slice(ck_dec, k.astype(ck_dec.dtype), (0, di, 0, 0))
    cv_dec = jax.lax.dynamic_update_slice(cv_dec, v.astype(cv_dec.dtype), (0, di, 0, 0))
    m_pref, m_dec = attn_mask
    out = ring.seq_cached_decode_attention(
        q, ck_pref.astype(q.dtype), cv_pref.astype(q.dtype),
        ck_dec.astype(q.dtype), cv_dec.astype(q.dtype),
        m_pref, m_dec, axis_name="seq",
    )
    return layers.out_project(out, p), ((ck_pref, ck_dec), (cv_pref, cv_dec))


def gpt2_block(x, p, cfg, positions, layer_cache, cache_index, attn_mask=None, std_layout=False, kv_tables=None, key_positions=None):
    """-> (x, new_cache, aux): aux is the MoE load-balance term (0 here).
    Shared by the gpt2 and opt families (pre-LN + learned positions);
    cfg.activation picks the MLP nonlinearity (gelu vs relu)."""
    h = layers.layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"], cfg.norm_eps)
    attn_out, new_cache = _attention(h, p["attn"], cfg, positions, layer_cache, cache_index, use_rope=False, attn_mask=attn_mask, std_layout=std_layout, kv_tables=kv_tables, key_positions=key_positions)
    x = x + attn_out
    h = layers.layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"], cfg.norm_eps)
    x = x + layers.mlp_gelu(h, p["mlp"], cfg.activation)
    return x, new_cache, jnp.float32(0.0)


def llama_block(x, p, cfg, positions, layer_cache, cache_index, attn_mask=None, std_layout=False, kv_tables=None, key_positions=None):
    """-> (x, new_cache, aux): aux is the MoE load-balance term."""
    h = layers.rms_norm(x, p["ln1"]["scale"], cfg.norm_eps)
    attn_out, new_cache = _attention(h, p["attn"], cfg, positions, layer_cache, cache_index, use_rope=True, attn_mask=attn_mask, std_layout=std_layout, kv_tables=kv_tables, key_positions=key_positions)
    x = x + attn_out
    h = layers.rms_norm(x, p["ln2"]["scale"], cfg.norm_eps)
    if "router" in p["mlp"]:  # MoE block (cfg.num_experts > 0)
        mlp_out, aux = layers.moe_swiglu(h, p["mlp"], cfg)
        return x + mlp_out, new_cache, aux
    x = x + layers.mlp_swiglu(h, p["mlp"], cfg.gate_act)
    return x, new_cache, jnp.float32(0.0)


def neox_block(x, p, cfg, positions, layer_cache, cache_index, attn_mask=None, std_layout=False, kv_tables=None, key_positions=None):
    """GPT-NeoX/Pythia: LayerNorm + (partial) rotary + optionally PARALLEL
    residual — out = x + attn(ln1 x) + mlp(ln2 x), both norms reading the
    SAME input (HF use_parallel_residual, the NeoX default); sequential
    pre-LN otherwise.  -> (x, new_cache, aux)."""
    h = layers.layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"], cfg.norm_eps)
    attn_out, new_cache = _attention(h, p["attn"], cfg, positions, layer_cache, cache_index, use_rope=True, attn_mask=attn_mask, std_layout=std_layout, kv_tables=kv_tables, key_positions=key_positions)
    if cfg.parallel_residual:
        h2 = layers.layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"], cfg.norm_eps)
        return x + attn_out + layers.mlp_gelu(h2, p["mlp"], cfg.activation), new_cache, jnp.float32(0.0)
    x = x + attn_out
    h2 = layers.layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"], cfg.norm_eps)
    return x + layers.mlp_gelu(h2, p["mlp"], cfg.activation), new_cache, jnp.float32(0.0)


BLOCK_FNS = {"gpt2": gpt2_block, "opt": gpt2_block, "llama": llama_block,
             "neox": neox_block}


def run_blocks(
    x: jax.Array,
    blocks: Params,
    cfg: ModelConfig,
    positions: jax.Array,
    cache_k: jax.Array | None,  # [L, B, S, KVH, HD] slice for these blocks
    cache_v: jax.Array | None,
    cache_index: jax.Array | None,
    remat: bool = False,
    attn_mask: jax.Array | None = None,
    std_layout: bool = False,
    kv_tables: jax.Array | None = None,
    key_positions: jax.Array | None = None,  # see _attention
    cache_sk: jax.Array | None = None,  # [L, NB, BLK, KVH] f32 absmax
    #   scales of an int8 page pool (QuantKVCache); layer_cache becomes a
    #   4-tuple per layer and the paged decode reads/writes quantized
    cache_sv: jax.Array | None = None,
) -> tuple[jax.Array, tuple | None, jax.Array]:
    """Scan the stacked blocks over x.  Used both for the whole model and for
    a single pipeline stage (blocks then hold only the stage's layer slice).
    Returns (x, caches, aux) — aux sums the MoE load-balance terms.

    Blocks may carry ``QuantizedTensor`` leaves (weight-only quantized
    serving): weights live in HBM at int8/int4 and flow through the scan to
    each matmul site, where layers._contract runs the fused dequant-matmul
    Pallas kernel (ops/quant_matmul.py) on TPU — the weights are read at
    their quantized width and never materialized full-dtype in HBM."""
    block_fn = BLOCK_FNS[cfg.family]

    if cache_k is None:
        def body(carry, layer_params):
            y, _, aux = block_fn(carry, layer_params, cfg, positions, None, None, attn_mask, std_layout)
            return y, aux

        if remat:
            body = jax.checkpoint(body)
        x, auxs = jax.lax.scan(body, x, blocks)
        return x, None, jnp.sum(auxs)

    if cache_sk is not None:
        def body_q(carry, xs):
            layer_params, ck, cv, sk, sv = xs
            y, new_cache, aux = block_fn(carry, layer_params, cfg, positions, (ck, cv, sk, sv), cache_index, attn_mask, std_layout, kv_tables, key_positions)
            return y, (new_cache, aux)

        if remat:
            body_q = jax.checkpoint(body_q)
        x, (new_cache, auxs) = jax.lax.scan(
            body_q, x, (blocks, cache_k, cache_v, cache_sk, cache_sv)
        )
        return x, new_cache, jnp.sum(auxs)

    def body(carry, xs):
        layer_params, ck, cv = xs
        y, new_cache, aux = block_fn(carry, layer_params, cfg, positions, (ck, cv), cache_index, attn_mask, std_layout, kv_tables, key_positions)
        return y, (new_cache, aux)

    if remat:
        body = jax.checkpoint(body)
    x, ((new_k, new_v), auxs) = jax.lax.scan(body, x, (blocks, cache_k, cache_v))
    return x, (new_k, new_v), jnp.sum(auxs)


# ---------------------------------------------------------------------------
# Full model forward
# ---------------------------------------------------------------------------

def embed(params: Params, cfg: ModelConfig, tokens: jax.Array, positions: jax.Array) -> jax.Array:
    x = jnp.take(params["embed"]["wte"], tokens, axis=0)
    if cfg.family in ("gpt2", "opt"):
        # OPT's learned position table carries HF's historical offset of 2
        # (OPTLearnedPositionalEmbedding); the converted table keeps it.
        off = 2 if cfg.family == "opt" else 0
        x = x + jnp.take(params["embed"]["wpe"], positions + off, axis=0)
    x = x.astype(jnp.dtype(cfg.dtype))
    if cfg.embed_scale != 1.0:
        # Gemma scales embeddings by sqrt(hidden) in the compute dtype
        # (HF casts the normalizer to hidden_states.dtype before the mul).
        x = x * jnp.asarray(cfg.embed_scale, x.dtype)
    return x


def unembed(params: Params, cfg: ModelConfig, x: jax.Array) -> jax.Array:
    if cfg.family in ("gpt2", "opt", "neox"):
        x = layers.layer_norm(x, params["final_norm"]["scale"], params["final_norm"]["bias"], cfg.norm_eps)
    else:
        x = layers.rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)
    if cfg.tie_embeddings:
        w = params["embed"]["wte"].T  # [D, V]
    else:
        w = params["lm_head"]["w"]
    return jnp.einsum(
        "btd,dv->btv", x, w.astype(x.dtype), preferred_element_type=jnp.float32
    )


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [B, T] int32
    positions: jax.Array | None = None,  # [B, T] int32
    cache: KVCache | None = None,
    cache_index: jax.Array | None = None,  # scalar int32 write offset, or
    #   [B] int32 per-row offsets (continuous batching; attn_mask required)
    remat: bool = False,
    attn_mask: jax.Array | None = None,  # broadcastable to [B, H, Tq, S]; True = attend
    return_aux: bool = False,  # also return the MoE load-balance aux loss
    kv_tables: jax.Array | None = None,  # [B, P] page table: the cache holds
    #   page POOLS [L, NB, BLK, KVH, HD] (paged continuous batching; see
    #   _attention's kv_tables contract — decode-only)
    key_positions: jax.Array | None = None,  # [B, S] true RoPE positions of
    #   cache slots, for the sliding-window mask under gapped (right-padded
    #   generate) cache layouts — see _attention's parameter comment
) -> tuple[jax.Array, KVCache | None] | tuple[jax.Array, KVCache | None, jax.Array]:
    """Full forward.  Returns (logits [B, T, V] float32, updated cache), plus
    the summed MoE aux loss when ``return_aux`` (scale by
    cfg.moe_aux_loss_weight and add to the task loss when training MoE).

    Contract: ``cache_index + T`` must not exceed ``cache.max_len`` — XLA's
    ``dynamic_update_slice`` clamps out-of-range starts, which would silently
    overwrite the last cache slot.  The decode loop in runtime/ enforces this
    statically (max_decode_steps + prompt_len <= max_seq_len)."""
    b, t = tokens.shape
    # Standard layout: forward generated the positions itself with no cache
    # offset — query rows align with key slots, which lets the flash kernel
    # take its static-causal fast path (no per-tile position masks).
    std_layout = positions is None and (cache_index is None or cache is None)
    if positions is None:
        base = cache_index if cache_index is not None else 0
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32) + base, (b, t))
    x = embed(params, cfg, tokens, positions)
    if cache is None:
        x, _, aux = run_blocks(x, params["blocks"], cfg, positions, None, None, None, remat, attn_mask, std_layout)
        out = (unembed(params, cfg, x), None)
    elif isinstance(cache, QuantKVCache):
        # Int8 page pool: decode-only (the per-step quantized write and the
        # scale-fused attention read both live on the kv_tables path).
        if kv_tables is None:
            raise ValueError(
                "QuantKVCache serves paged decode only (pass kv_tables); "
                "prefill runs against full-width transient rows"
            )
        x, (new_k, new_v, new_sk, new_sv), aux = run_blocks(
            x, params["blocks"], cfg, positions, cache.k, cache.v,
            cache_index, remat, attn_mask, std_layout, kv_tables,
            key_positions, cache_sk=cache.k_scale, cache_sv=cache.v_scale,
        )
        out = (unembed(params, cfg, x), QuantKVCache(
            k=new_k, v=new_v, k_scale=new_sk, v_scale=new_sv,
            row_dtype=cache.row_dtype,
        ))
    else:
        x, (new_k, new_v), aux = run_blocks(
            x, params["blocks"], cfg, positions, cache.k, cache.v, cache_index, remat, attn_mask, std_layout, kv_tables, key_positions
        )
        out = (unembed(params, cfg, x), KVCache(k=new_k, v=new_v))
    return (*out, aux) if return_aux else out


# ---------------------------------------------------------------------------
# Random init (tests, benchmarks; real weights come from checkpoint/)
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: ModelConfig, dtype: Any = None) -> Params:
    dtype = dtype or jnp.dtype(cfg.dtype)
    L, D, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    H, KVH, HD = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    keys = iter(jax.random.split(rng, 32))

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * (fan_in**-0.5)).astype(dtype)

    params: Params = {
        "embed": {"wte": dense(next(keys), (cfg.vocab_size, D), D)},
        "final_norm": {"scale": jnp.ones((D,), dtype)},
    }
    if cfg.family in ("gpt2", "opt", "neox"):
        if cfg.family != "neox":  # neox uses rotary, not a position table
            pos_rows = cfg.max_seq_len + (2 if cfg.family == "opt" else 0)
            params["embed"]["wpe"] = dense(next(keys), (pos_rows, D), D)
        params["final_norm"]["bias"] = jnp.zeros((D,), dtype)
        params["blocks"] = {
            "ln1": {"scale": jnp.ones((L, D), dtype), "bias": jnp.zeros((L, D), dtype)},
            "ln2": {"scale": jnp.ones((L, D), dtype), "bias": jnp.zeros((L, D), dtype)},
            "attn": {
                "wq": dense(next(keys), (L, D, H, HD), D),
                "wk": dense(next(keys), (L, D, KVH, HD), D),
                "wv": dense(next(keys), (L, D, KVH, HD), D),
                "wo": dense(next(keys), (L, H, HD, D), H * HD),
                "bq": jnp.zeros((L, H, HD), dtype),
                "bk": jnp.zeros((L, KVH, HD), dtype),
                "bv": jnp.zeros((L, KVH, HD), dtype),
                "bo": jnp.zeros((L, D), dtype),
            },
            "mlp": {
                "w_in": dense(next(keys), (L, D, F), D),
                "b_in": jnp.zeros((L, F), dtype),
                "w_out": dense(next(keys), (L, F, D), F),
                "b_out": jnp.zeros((L, D), dtype),
            },
        }
    elif cfg.family == "llama":
        if cfg.num_experts > 0:
            E = cfg.num_experts
            mlp = {
                "router": dense(next(keys), (L, D, E), D),
                "w_gate": dense(next(keys), (L, E, D, F), D),
                "w_up": dense(next(keys), (L, E, D, F), D),
                "w_down": dense(next(keys), (L, E, F, D), F),
            }
        else:
            mlp = {
                "w_gate": dense(next(keys), (L, D, F), D),
                "w_up": dense(next(keys), (L, D, F), D),
                "w_down": dense(next(keys), (L, F, D), F),
            }
        attn = {
            "wq": dense(next(keys), (L, D, H, HD), D),
            "wk": dense(next(keys), (L, D, KVH, HD), D),
            "wv": dense(next(keys), (L, D, KVH, HD), D),
            "wo": dense(next(keys), (L, H, HD, D), H * HD),
        }
        if cfg.qkv_bias:  # Qwen2-style llama blocks
            attn["bq"] = jnp.zeros((L, H, HD), dtype)
            attn["bk"] = jnp.zeros((L, KVH, HD), dtype)
            attn["bv"] = jnp.zeros((L, KVH, HD), dtype)
        params["blocks"] = {
            "ln1": {"scale": jnp.ones((L, D), dtype)},
            "ln2": {"scale": jnp.ones((L, D), dtype)},
            "attn": attn,
            "mlp": mlp,
        }
    else:
        raise ValueError(f"unknown family {cfg.family!r}")
    if cfg.num_experts > 0 and cfg.family != "llama":
        raise ValueError("MoE (num_experts > 0) is supported for the llama family")
    if cfg.family == "neox" and cfg.tie_embeddings:
        raise ValueError("neox checkpoints untie embeddings (embed_out)")
    if not cfg.tie_embeddings:
        params["lm_head"] = {"w": dense(next(keys), (D, cfg.vocab_size), D)}
    return params


def count_params(params: Params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
