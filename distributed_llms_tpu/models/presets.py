"""Named model presets covering the BASELINE.json ladder configs
(GPT-2-125M -> TinyLlama-1.1B -> Llama-2-7B -> Llama-2-13B -> Llama-3-70B)
plus tiny variants for tests.  Replaces the reference's single hard-coded
model id (run_master.py:17, "facebook/opt-125m")."""

from __future__ import annotations

from dataclasses import replace

from ..core.config import ModelConfig

PRESETS: dict[str, ModelConfig] = {
    "gpt2-125m": ModelConfig(
        family="gpt2", vocab_size=50257, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, num_kv_heads=12, max_seq_len=1024,
        norm_eps=1e-5, tie_embeddings=True,
    ),
    "gpt2-medium": ModelConfig(
        family="gpt2", vocab_size=50257, hidden_size=1024, intermediate_size=4096,
        num_layers=24, num_heads=16, num_kv_heads=16, max_seq_len=1024,
        norm_eps=1e-5, tie_embeddings=True,
    ),
    # The reference's default model (run_master.py:17).
    "opt-125m": ModelConfig(
        family="opt", vocab_size=50272, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, num_kv_heads=12, max_seq_len=2048,
        norm_eps=1e-5, tie_embeddings=True, activation="relu",
    ),
    "tinyllama-1.1b": ModelConfig(
        family="llama", vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_layers=22, num_heads=32, num_kv_heads=4, max_seq_len=2048,
        rope_theta=10000.0, norm_eps=1e-5, tie_embeddings=False,
    ),
    "llama-2-7b": ModelConfig(
        family="llama", vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_layers=32, num_heads=32, num_kv_heads=32, max_seq_len=4096,
        rope_theta=10000.0, norm_eps=1e-5, tie_embeddings=False,
    ),
    "llama-2-13b": ModelConfig(
        family="llama", vocab_size=32000, hidden_size=5120, intermediate_size=13824,
        num_layers=40, num_heads=40, num_kv_heads=40, max_seq_len=4096,
        rope_theta=10000.0, norm_eps=1e-5, tie_embeddings=False,
    ),
    "llama-3-8b": ModelConfig(
        family="llama", vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=8192,
        rope_theta=500000.0, norm_eps=1e-5, tie_embeddings=False,
    ),
    "llama-3.1-8b": ModelConfig(
        family="llama", vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=131072,
        rope_theta=500000.0, norm_eps=1e-5, tie_embeddings=False,
        rope_scaling_factor=8.0, rope_low_freq_factor=1.0,
        rope_high_freq_factor=4.0, rope_original_max_len=8192,
    ),
    "llama-3-70b": ModelConfig(
        family="llama", vocab_size=128256, hidden_size=8192, intermediate_size=28672,
        num_layers=80, num_heads=64, num_kv_heads=8, max_seq_len=8192,
        rope_theta=500000.0, norm_eps=1e-5, tie_embeddings=False,
    ),
    "qwen2-7b": ModelConfig(
        family="llama", qkv_bias=True, vocab_size=152064, hidden_size=3584,
        intermediate_size=18944, num_layers=28, num_heads=28, num_kv_heads=4,
        max_seq_len=32768, rope_theta=1e6, norm_eps=1e-6, tie_embeddings=False,
    ),
    "gemma-7b": ModelConfig(
        family="llama", gate_act="gelu_tanh", norm_plus_one=True,
        embed_scale=3072.0**0.5, vocab_size=256000, hidden_size=3072,
        intermediate_size=24576, num_layers=28, num_heads=16, num_kv_heads=16,
        head_dim=256, max_seq_len=8192, rope_theta=10000.0, norm_eps=1e-6,
        tie_embeddings=True,
    ),
    "phi-3-mini-4k": ModelConfig(
        family="llama", sliding_window=2047, vocab_size=32064,
        hidden_size=3072, intermediate_size=8192, num_layers=32,
        num_heads=32, num_kv_heads=32, max_seq_len=4096,
        rope_theta=10000.0, norm_eps=1e-5, tie_embeddings=False,
    ),
    "mistral-7b": ModelConfig(
        family="llama", sliding_window=4096, vocab_size=32000, hidden_size=4096,
        intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
        max_seq_len=32768, rope_theta=10000.0, norm_eps=1e-5,
        tie_embeddings=False,
    ),
    "pythia-6.9b": ModelConfig(
        family="neox", vocab_size=50432, hidden_size=4096,
        intermediate_size=16384, num_layers=32, num_heads=32,
        num_kv_heads=32, max_seq_len=2048, rope_theta=10000.0,
        rotary_pct=0.25, parallel_residual=True, norm_eps=1e-5,
        tie_embeddings=False, activation="gelu_exact",
    ),
    "mixtral-8x7b": ModelConfig(
        family="llama", vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=32768,
        rope_theta=1e6, norm_eps=1e-5, tie_embeddings=False,
        num_experts=8, num_experts_per_token=2,
    ),
    # Tiny configs for unit tests / CPU fake-mesh integration tests.
    "moe-tiny": ModelConfig(
        family="llama", vocab_size=256, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
        tie_embeddings=False, dtype="float32",
        num_experts=4, num_experts_per_token=2,
    ),
    "gpt2-tiny": ModelConfig(
        family="gpt2", vocab_size=256, hidden_size=64, intermediate_size=256,
        num_layers=4, num_heads=4, num_kv_heads=4, max_seq_len=128,
        tie_embeddings=True, dtype="float32",
    ),
    "opt-tiny": ModelConfig(
        family="opt", vocab_size=256, hidden_size=64, intermediate_size=256,
        num_layers=4, num_heads=4, num_kv_heads=4, max_seq_len=128,
        tie_embeddings=True, dtype="float32", activation="relu",
    ),
    "neox-tiny": ModelConfig(
        family="neox", vocab_size=256, hidden_size=64, intermediate_size=176,
        num_layers=3, num_heads=4, num_kv_heads=4, max_seq_len=128,
        rotary_pct=0.25, parallel_residual=True, tie_embeddings=False,
        dtype="float32", activation="gelu_exact",
    ),
    "llama-tiny": ModelConfig(
        family="llama", vocab_size=256, hidden_size=64, intermediate_size=176,
        num_layers=4, num_heads=4, num_kv_heads=2, max_seq_len=128,
        tie_embeddings=False, dtype="float32",
    ),
}

# HF hub repo ids for the checkpoint converter.
HF_REPOS: dict[str, str] = {
    "gpt2-125m": "gpt2",
    "gpt2-medium": "gpt2-medium",
    "opt-125m": "facebook/opt-125m",
    "tinyllama-1.1b": "TinyLlama/TinyLlama-1.1B-Chat-v1.0",
    "llama-2-7b": "meta-llama/Llama-2-7b-hf",
    "llama-2-13b": "meta-llama/Llama-2-13b-hf",
    "llama-3-8b": "meta-llama/Meta-Llama-3-8B",
    "llama-3.1-8b": "meta-llama/Llama-3.1-8B",
    "llama-3-70b": "meta-llama/Meta-Llama-3-70B",
    "qwen2-7b": "Qwen/Qwen2-7B",
    "gemma-7b": "google/gemma-7b",
    "mistral-7b": "mistralai/Mistral-7B-v0.1",
    "phi-3-mini-4k": "microsoft/Phi-3-mini-4k-instruct",
    "pythia-6.9b": "EleutherAI/pythia-6.9b",
}


def get_preset(name: str, **overrides) -> ModelConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    cfg = PRESETS[name]
    return replace(cfg, **overrides) if overrides else cfg
