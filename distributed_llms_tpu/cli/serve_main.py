"""Standalone HTTP serving entry point.

``python -m distributed_llms_tpu.cli.serve_main --store ./store_7b --port 8000``
boots an InferenceEngine from a shard store, wraps its continuous batcher in
the OpenAI-style HTTP gateway (runtime/server.py), and serves until SIGTERM/
SIGINT.  This is the single-process serving front door; the cluster path
(cli/coordinator_main.py --serve) remains the multi-worker one.

The reference has no serving entry point at all — its user interface is the
master REPL (run_master.py:28-42).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal

from ..core.config import load_config
from ..core.observability import get_logger
from ..runtime.engine import InferenceEngine
from ..runtime.server import InferenceServer

log = get_logger("serve_main")

# Flag <-> config contract, pinned by graftlint (GL303): every dlt-serve
# flag is declared in exactly one of these two tables.  _RUNTIME_FLAGS
# maps a flag to the RuntimeConfig field it shadows (the flag wins when
# given; the field is the config-file/--override spelling) — field
# existence is checked against core/config.py, so a rename there breaks
# the gate here instead of silently orphaning the flag.
_RUNTIME_FLAGS: dict[str, str] = {
    "max-len": "max_seq_len",
    "paged-pages": "paged_pages",
    "page-size": "page_size",
    "prefix-cache": "prefix_cache",
    "kv-bits": "kv_bits",
    "host-pages": "host_pages",
    "overlap": "overlap",
    "schedule": "schedule",
    "token-budget": "token_budget",
    "request-timeout": "request_timeout_s",
    "shed-cost-factor": "shed_cost_factor",
    "constrained": "constrained_decoding",
    "constrain-cache": "constrain_cache_size",
    "spec-decode": "spec_decode",
    "spec-k": "spec_k",
    "spec-adaptive-k": "spec_adaptive_k",
    "tenant-weights": "tenant_weights",
    "tenant-quota-tps": "tenant_quota_tps",
    "tenant-max-rows": "tenant_max_rows",
    "fault": "faults",
}
# Server plumbing with no RuntimeConfig twin (transport, process, and
# batcher-shape knobs that only make sense per serving process).
_SERVER_ONLY_FLAGS = frozenset({
    "store", "preset", "config", "override", "host", "port", "model-name",
    "slots", "chunk-steps", "prefill-chunk", "prefill-concurrency",
    "max-pending", "drain-timeout", "watchdog-timeout", "platform",
    "replicas", "probe-interval", "failover-retries",
    "disaggregate", "prefill-replicas", "decode-replicas",
    "prefill-replicas-max", "decode-replicas-max",
    "replicas-min", "replicas-max", "autoscale-interval",
    "autoscale-up-load", "autoscale-down-load", "autoscale-cooldown",
    "autoscale-hysteresis",
})


def _build_engine(args):
    """Shared boot: config + fault plane + engine.  Returns
    (engine, default model name, runtime config, fault plane, fault
    spec) — fleet mode re-parses the spec into a plane PER REPLICA."""
    cfg = load_config(args.config, args.override)
    rt = cfg.runtime
    # Speculative knobs must land on the RuntimeConfig BEFORE the engine
    # builds: the engine attaches its self-draft at construction from
    # rt.spec_decode (flag wins when given; the field is the config-file
    # spelling, like every _RUNTIME_FLAGS entry).
    spec_overrides = {
        field: val for field, val in (
            ("spec_decode", args.spec_decode),
            ("spec_k", args.spec_k),
            ("spec_adaptive_k", args.spec_adaptive_k),
        ) if val is not None
    }
    if spec_overrides:
        import dataclasses

        rt = dataclasses.replace(rt, **spec_overrides)
    # Parse the fault spec BEFORE the (slow) engine build: an operator's
    # typo'd site must fail the boot in milliseconds, not after a full
    # model load.  strict=True checks sites against FAULT_SITES — a rule
    # that could never fire is config drift, not a no-op.
    faults = None
    fault_spec = ",".join(args.fault or []) or rt.faults
    if fault_spec:
        from ..runtime.faults import FaultPlane

        faults = FaultPlane.parse(fault_spec, strict=True)
        log.warning("fault injection armed: %s", faults.describe())
    if args.store:
        mesh_cfg = cfg.mesh if cfg.mesh.num_devices > 1 else None
        engine = InferenceEngine.from_store(args.store, rt=rt, mesh_cfg=mesh_cfg)
        default_name = os.path.basename(os.path.normpath(args.store))
    elif args.preset:
        # Random-weight smoke serving (no checkpoint needed): exercises the
        # full HTTP/batcher/decode path with a byte-level tokenizer.  Tiny
        # presets (vocab 256) cannot hold the byte tokenizer's specials
        # (259 ids) — widen to a lane-aligned 512.
        from ..models.presets import get_preset
        from ..runtime.tokenizer import ByteTokenizer

        overrides = (
            {"vocab_size": 512}
            if get_preset(args.preset).vocab_size < ByteTokenizer.vocab_size
            else {}
        )
        engine = InferenceEngine.from_preset(args.preset, rt=rt, **overrides)
        default_name = args.preset
    else:
        raise SystemExit("one of --store or --preset is required")
    return engine, default_name, rt, faults, fault_spec


def _server_factory(args, engine, default_name, rt, faults, *,
                    host=None, port=None, role="colocated",
                    backstop_x=None):
    """() -> a fresh, unstarted InferenceServer over a fresh batcher.
    Replicas share the engine's weights by reference; each gets its own
    pool/caches/supervisor."""

    def make_batcher():
        # Called at boot and again by the supervisor after an engine
        # crash: a respawn must share the already-armed fault plane (rules
        # that fired stay fired) while rebuilding pool + caches fresh.
        return engine.continuous_batcher(
            batch_slots=args.slots,
            max_len=args.max_len,
            chunk_steps=args.chunk_steps,
            prefill_chunk=args.prefill_chunk,
            prefill_concurrency=args.prefill_concurrency,
            paged_pages=args.paged_pages,
            page_size=args.page_size,
            prefix_cache=args.prefix_cache,
            kv_bits=args.kv_bits,
            host_pages=args.host_pages,
            overlap=(None if args.overlap is None
                     else args.overlap == "on"),
            schedule=args.schedule,
            token_budget=args.token_budget,
            tenant_weights=args.tenant_weights,
            tenant_max_rows=args.tenant_max_rows,
            faults=faults,
        )

    # Size the compiled-constraint LRU once per serving process (the
    # cache is module-level: replicas and respawns share remembered
    # automata by design).
    from ..runtime import constrain as constrain_lib

    constrain_lib.configure_cache(
        args.constrain_cache if args.constrain_cache is not None
        else rt.constrain_cache_size
    )

    # Tenant QoS (the gateway half): flag wins, config-file field is the
    # fallback, exactly like every _RUNTIME_FLAGS knob.  Weights parse
    # ONCE here so a typo'd spec fails the boot in milliseconds.
    from ..runtime.scheduler import parse_tenant_weights

    tenant_weights = parse_tenant_weights(
        args.tenant_weights if args.tenant_weights is not None
        else rt.tenant_weights
    )
    tenant_quota_tps = (args.tenant_quota_tps
                        if args.tenant_quota_tps is not None
                        else rt.tenant_quota_tps)

    def make_server():
        return InferenceServer(
            make_batcher(),
            model_name=args.model_name or default_name,
            host=args.host if host is None else host,
            port=args.port if port is None else port,
            max_pending=args.max_pending,
            batcher_factory=make_batcher,
            request_timeout_s=(args.request_timeout
                               if args.request_timeout is not None
                               else rt.request_timeout_s),
            watchdog_timeout_s=args.watchdog_timeout,
            shed_cost_factor=(args.shed_cost_factor
                              if args.shed_cost_factor is not None
                              else rt.shed_cost_factor),
            role=role,
            constrained=(args.constrained if args.constrained is not None
                         else rt.constrained_decoding),
            tenant_weights=tenant_weights,
            tenant_quota_tps=tenant_quota_tps,
            tenant_backstop_x=backstop_x,
        )

    return make_server


def build_server(args) -> InferenceServer:
    engine, default_name, rt, faults, _spec = _build_engine(args)
    return _server_factory(args, engine, default_name, rt, faults)()


def build_fleet(args):
    """``--replicas N`` (N >= 2) or ``--disaggregate``: full
    server/batcher stacks on ephemeral local ports behind a health-aware
    ReplicaRouter on --host/--port — exact failover, rolling
    drain/respawn (SIGHUP), and replica-scoped chaos via the --fault
    spec.  ``--disaggregate`` builds --prefill-replicas prefill-role +
    --decode-replicas decode-role stacks instead of N colocated ones;
    the router hands prompts to the prefill tier and ships finished KV
    pages to the decode replica before forwarding (degrading to
    colocated prefill whenever the handoff cannot complete).
    ``--replicas-min/--replicas-max`` boot an ELASTIC colocated fleet:
    replicas-min stacks now, a signal-driven autoscaler
    (cluster/autoscale.py) growing to replicas-max on router
    committed-token load and shrinking back via graceful drain only.
    With ``--disaggregate``, ``--replicas-max`` (or the per-tier
    ``--prefill-replicas-max``/``--decode-replicas-max``) arms the
    TIERED autoscaler instead: each tier scales independently between
    its boot count and its ceiling — prefill on handoff queue depth,
    decode on committed-token mass.  In every fleet mode the ROUTER
    owns the tenant rate ledger (one admission-commit point, so a
    fleet of N admits 1x quota); replica gateways keep a loose 2x
    backstop so a bypassed router gate never leaves an unmetered path.
    Returns (fleet, router, autoscaler-or-None)."""
    from ..cluster.autoscale import Autoscaler, TieredAutoscaler, TierPolicy
    from ..cluster.fleet import ReplicaFleet
    from ..runtime.router import ReplicaRouter
    from ..runtime.scheduler import parse_tenant_weights

    engine, default_name, rt, faults, fault_spec = _build_engine(args)

    def replica_factory(role="colocated"):
        # Each replica gets its OWN plane parsed from the same spec: the
        # batcher.*/server-side rule counters are traversed by that
        # replica's engine thread alone (FaultPlane's thread contract),
        # and @N windows count per replica — sharing the fleet's plane
        # across N engine threads would race the counters and let a
        # replica-scoped stall drill wedge whichever replica decodes
        # next.  The shared ``faults`` plane keeps the replica.*/router.*
        # sites, which only the event loop traverses.
        plane = None
        if fault_spec:
            from ..runtime.faults import FaultPlane

            plane = FaultPlane.parse(fault_spec, strict=True)
        # backstop_x: behind a router the replica gateway is NOT the
        # admission-commit point — the router's fleet ledger is.  The
        # replica keeps a loose ~2x-fair-share backstop so a drilled or
        # bypassed router gate still meters (never a silent unmetered
        # path), without double-shedding honest traffic the router
        # already admitted.
        return _server_factory(args, engine, default_name, rt, plane,
                               host="127.0.0.1", port=0, role=role,
                               backstop_x=2.0)()

    if args.disaggregate:
        if args.prefill_replicas < 1 or args.decode_replicas < 1:
            raise SystemExit(
                "--disaggregate needs --prefill-replicas >= 1 and "
                "--decode-replicas >= 1"
            )
        paged = args.paged_pages if args.paged_pages is not None \
            else rt.paged_pages
        cache_on = args.prefix_cache if args.prefix_cache is not None \
            else rt.prefix_cache
        if not paged or not cache_on:
            raise SystemExit(
                "--disaggregate ships content-addressed KV pool pages: it "
                "needs --paged-pages and --prefix-cache on every replica"
            )
        import functools

        factories = (
            [functools.partial(replica_factory, "prefill")]
            * args.prefill_replicas
            + [functools.partial(replica_factory, "decode")]
            * args.decode_replicas
        )
        names = [f"p{i}" for i in range(args.prefill_replicas)] \
            + [f"d{i}" for i in range(args.decode_replicas)]
    else:
        n = args.replicas_min if args.replicas_max else args.replicas
        factories = [replica_factory] * n
        names = None
    fleet = ReplicaFleet(
        factories, names=names,
        probe_interval_s=args.probe_interval,
        faults=faults,
    )
    # The router is the fleet's one admission-commit point: the tenant
    # rate ledger lives HERE (quota conserved at any fleet size), with
    # the same flag-wins-else-config resolution the gateways use.
    tenant_weights = parse_tenant_weights(
        args.tenant_weights if args.tenant_weights is not None
        else rt.tenant_weights
    )
    tenant_quota_tps = (args.tenant_quota_tps
                        if args.tenant_quota_tps is not None
                        else rt.tenant_quota_tps)
    router = ReplicaRouter(
        fleet, host=args.host, port=args.port,
        tokenizer=engine.tokenizer,
        page_size=(args.page_size or rt.page_size or 64),
        max_failover_retries=args.failover_retries,
        faults=faults,
        handoff=bool(args.disaggregate),
        # Affinity/handoff digests must match the fleet's pool digests,
        # which are salted by the KV width (--kv-bits) — a mismatched
        # salt would read as a digest mismatch on every handoff.
        kv_bits=(args.kv_bits if args.kv_bits is not None else rt.kv_bits),
        tenant_weights=tenant_weights,
        tenant_quota_tps=tenant_quota_tps,
    )
    autoscaler = None
    if args.disaggregate:
        # Tier ceilings: the per-tier flag wins, --replicas-max is the
        # shared spelling, the boot count means "fixed tier".
        p_max = (args.prefill_replicas_max or args.replicas_max
                 or args.prefill_replicas)
        d_max = (args.decode_replicas_max or args.replicas_max
                 or args.decode_replicas)
        if p_max < args.prefill_replicas or d_max < args.decode_replicas:
            raise SystemExit(
                f"tier ceiling below its boot count: prefill "
                f"{args.prefill_replicas}..{p_max}, decode "
                f"{args.decode_replicas}..{d_max}"
            )
        if p_max > args.prefill_replicas or d_max > args.decode_replicas:
            import functools

            autoscaler = TieredAutoscaler(
                fleet,
                prefill=TierPolicy(
                    min_replicas=args.prefill_replicas,
                    max_replicas=p_max,
                    up_load=args.autoscale_up_load,
                    down_load=args.autoscale_down_load,
                    hysteresis=args.autoscale_hysteresis,
                    cooldown_s=args.autoscale_cooldown,
                ),
                decode=TierPolicy(
                    min_replicas=args.decode_replicas,
                    max_replicas=d_max,
                    up_load=args.autoscale_up_load,
                    down_load=args.autoscale_down_load,
                    hysteresis=args.autoscale_hysteresis,
                    cooldown_s=args.autoscale_cooldown,
                ),
                prefill_factory=functools.partial(replica_factory,
                                                  "prefill"),
                decode_factory=functools.partial(replica_factory,
                                                 "decode"),
                interval_s=args.autoscale_interval,
                drain_timeout_s=args.drain_timeout,
                faults=faults,
            )
    elif args.replicas_max:
        if args.replicas_max < args.replicas_min:
            raise SystemExit(
                f"--replicas-max {args.replicas_max} < --replicas-min "
                f"{args.replicas_min}"
            )
        if args.replicas != 1:
            raise SystemExit(
                "--replicas fixes the fleet size; an elastic fleet is "
                "sized by --replicas-min/--replicas-max"
            )
        autoscaler = Autoscaler(
            fleet,
            min_replicas=args.replicas_min,
            max_replicas=args.replicas_max,
            interval_s=args.autoscale_interval,
            up_load=args.autoscale_up_load,
            down_load=args.autoscale_down_load,
            hysteresis=args.autoscale_hysteresis,
            cooldown_s=args.autoscale_cooldown,
            drain_timeout_s=args.drain_timeout,
            faults=faults,
        )
    return fleet, router, autoscaler


async def _serve(args) -> None:
    stop = asyncio.Event()
    force = asyncio.Event()
    loop = asyncio.get_running_loop()

    def on_signal():
        # First signal: graceful drain.  Second: cut the drain short.
        (force if stop.is_set() else stop).set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, on_signal)
    if args.replicas > 1 or args.disaggregate or args.replicas_max:
        fleet, router, autoscaler = build_fleet(args)
        await fleet.start()
        host, port = await router.start()
        # Replicas boot in state "starting" and only a healthy probe makes
        # them routable — announce ready only once the fleet can actually
        # place work, or the first requests shed 503 off an idle fleet.
        if not await fleet.wait_healthy(timeout_s=60.0):
            log.warning(
                "not every replica probed healthy within 60s; serving with "
                "%d routable", fleet.report()["healthy"],
            )
        # SIGHUP: zero-downtime rolling restart of the whole fleet, one
        # replica at a time (config reload drills, binary swaps).  One
        # restart at a time: a second SIGHUP mid-walk would interleave
        # two drain/respawn passes over the same handles — overwriting
        # h.server orphans a freshly-booted replica (leaked socket +
        # engine thread + pool) and can leave every replica draining at
        # once.  Failures must surface, not die as unretrieved task
        # exceptions.
        restart_task: list[asyncio.Task | None] = [None]

        def on_hup():
            t = restart_task[0]
            if t is not None and not t.done():
                log.warning("SIGHUP ignored: a rolling restart is "
                            "already in progress")
                return

            async def run():
                try:
                    await fleet.rolling_restart(
                        drain_timeout_s=args.drain_timeout
                    )
                    log.info("rolling restart complete")
                except Exception:
                    log.exception("rolling restart failed")

            restart_task[0] = asyncio.ensure_future(run())

        loop.add_signal_handler(signal.SIGHUP, on_hup)
        if autoscaler is not None:
            # Flat or tiered — each logs its own bounds in start().
            await autoscaler.start()
        log.info("fleet of %d ready on http://%s:%s (SIGHUP = rolling "
                 "restart; Ctrl-C to stop)", len(fleet.replicas), host, port)
        await stop.wait()
        log.info("shutting down fleet...")
        if autoscaler is not None:
            await autoscaler.stop()
        await router.stop()
        await fleet.stop()
        return
    server = build_server(args)
    host, port = await server.start()
    log.info("ready on http://%s:%s (Ctrl-C to stop)", host, port)
    await stop.wait()
    log.info("shutting down (draining up to %.0fs; signal again to force)...",
             args.drain_timeout)
    drain = asyncio.create_task(server.stop(drain_timeout=args.drain_timeout))
    forcer = asyncio.create_task(force.wait())
    await asyncio.wait({drain, forcer}, return_when=asyncio.FIRST_COMPLETED)
    if not drain.done():
        log.info("second signal: forcing immediate shutdown")
        server.force_stop()
    await drain
    forcer.cancel()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--store", default=None, help="shard store directory")
    ap.add_argument("--preset", default=None,
                    help="serve a random-weight preset (smoke testing)")
    ap.add_argument("--config", default=None, help="JSON/YAML config file")
    ap.add_argument("--override", action="append", default=[],
                    help="dotted config override, e.g. runtime.temperature=0.7")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--model-name", default=None,
                    help="name reported by /v1/models (default: store/preset)")
    ap.add_argument("--slots", type=int, default=8,
                    help="continuous-batching row slots")
    ap.add_argument("--max-len", type=int, default=None,
                    help="per-row cache length (default: runtime.max_seq_len)")
    ap.add_argument("--chunk-steps", type=int, default=8,
                    help="decode steps per scheduling chunk")
    ap.add_argument("--paged-pages", type=int, default=None,
                    help="paged KV: size of the shared page pool (pages); "
                         "rows allocate only what prompt+budget need and a "
                         "dry pool back-pressures admission (default: "
                         "runtime.paged_pages; 0 forces contiguous)")
    ap.add_argument("--page-size", type=int, default=None,
                    help="paged KV: tokens per page (default: "
                         "runtime.page_size)")
    ap.add_argument("--kv-bits", type=int, default=None, choices=[16, 8],
                    help="KV pool width: 8 stores pages as int8 with "
                         "blockwise absmax scales (~1.9x concurrent rows "
                         "per pool byte; greedy outputs parity-bounded, "
                         "not bit-exact).  Needs --paged-pages.  Default: "
                         "runtime.kv_bits (16)")
    ap.add_argument("--host-pages", type=int, default=None,
                    help="host-RAM KV tier size in pages: preemption swaps "
                         "rows out (byte-exact restore) and cold cached "
                         "pages spill before eviction.  Needs "
                         "--paged-pages.  Default: runtime.host_pages (0)")
    ap.add_argument("--prefix-cache", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="automatic prefix caching over the paged pool: "
                         "full prompt pages are content-hashed and reused "
                         "copy-free across requests (refcounted pages, LRU "
                         "eviction under pool pressure); needs --paged-pages."
                         "  Per-request opt-out: \"prefix_cache\": false.  "
                         "(default: runtime.prefix_cache)")
    ap.add_argument("--overlap", choices=["on", "off"], default=None,
                    help="dispatch-ahead engine loop: while no scheduling "
                         "work is pending, decode chunk N+1 dispatches "
                         "from the device-resident carry and chunk N's "
                         "host work (delivery, digest hashing, metrics) "
                         "overlaps its device execution.  Temp-0 bytes "
                         "identical on or off; gauges under "
                         "batcher_overlap_* on /metrics (default: "
                         "runtime.overlap, on)")
    ap.add_argument("--schedule", choices=["mixed", "alternate"],
                    default=None,
                    help="scheduling policy (runtime/scheduler.py): "
                         "'mixed' fuses pending prefill-chunk bites into "
                         "the decode step as one token-budget program so "
                         "decode rows never stall for a serialized "
                         "prefill forward; 'alternate' keeps the classic "
                         "serialized rounds.  Temp-0 bytes identical "
                         "either way (default: runtime.schedule, mixed)")
    ap.add_argument("--token-budget", type=int, default=None,
                    help="per-step token budget of the mixed schedule: "
                         "each fused step runs one decode leg per active "
                         "slot plus up to budget - n_active prefill "
                         "tokens; prompts longer than the budget "
                         "auto-chunk.  0 = prefill-chunk-sized bites "
                         "(default: runtime.token_budget)")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="chunked prefill: admit at most this many prompt "
                         "tokens per scheduling round per pending prefill, "
                         "so long prompts never stall in-flight decodes "
                         "(default: monolithic admission)")
    ap.add_argument("--prefill-concurrency", type=int, default=2,
                    help="chunked prefills in flight at once — two long "
                         "prompts interleave their admissions instead of "
                         "serializing (1 restores the old one-at-a-time "
                         "limit; per-round prefill work is bounded by "
                         "prefill-chunk x this)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="replica-fleet serving: run N independent "
                         "server/batcher stacks (each with its own "
                         "supervisor and KV pool) behind a health-aware "
                         "router on --port — exact failover on replica "
                         "crash/stall/partition, SIGHUP = zero-downtime "
                         "rolling restart (1 = single-server mode)")
    ap.add_argument("--disaggregate", action="store_true",
                    help="disaggregated serving: dedicated prefill-role "
                         "replicas run admission/chunked prefill and ship "
                         "finished KV pages to decode-role replicas over "
                         "the verified KV-handoff plane; any handoff "
                         "failure (prefill crash/stall, digest mismatch, "
                         "retry exhaustion) degrades to colocated prefill "
                         "on the decode replica, byte-exact either way.  "
                         "Requires --paged-pages and --prefix-cache; "
                         "ignores --replicas")
    ap.add_argument("--prefill-replicas", type=int, default=1,
                    help="prefill-role replicas under --disaggregate "
                         "(the tier's floor when a ceiling arms the "
                         "tiered autoscaler)")
    ap.add_argument("--decode-replicas", type=int, default=2,
                    help="decode-role replicas under --disaggregate "
                         "(the tier's floor when a ceiling arms the "
                         "tiered autoscaler)")
    ap.add_argument("--prefill-replicas-max", type=int, default=None,
                    help="elastic prefill-tier ceiling under "
                         "--disaggregate: the tiered autoscaler grows "
                         "the tier on handoff queue depth and shrinks "
                         "it via graceful drain, never below "
                         "--prefill-replicas (default: --replicas-max, "
                         "else fixed at the boot count)")
    ap.add_argument("--decode-replicas-max", type=int, default=None,
                    help="elastic decode-tier ceiling under "
                         "--disaggregate: scales on committed-token "
                         "mass over tier KV capacity, never below "
                         "--decode-replicas (default: --replicas-max, "
                         "else fixed at the boot count)")
    ap.add_argument("--replicas-min", type=int, default=1,
                    help="elastic fleet floor: boot this many colocated "
                         "replicas and never drain below it (used with "
                         "--replicas-max; the autoscaler scales between "
                         "the two on router committed-token load)")
    ap.add_argument("--replicas-max", type=int, default=None,
                    help="elastic fleet ceiling: arm the autoscaler "
                         "(cluster/autoscale.py) to grow the colocated "
                         "fleet up to this many replicas under load and "
                         "shrink back via graceful drain — in-flight "
                         "requests finish byte-exact, stragglers migrate "
                         "through the router's exact failover.  With "
                         "--disaggregate this is the PER-TIER ceiling "
                         "(each tier scales independently between its "
                         "boot count and this; --prefill/--decode-"
                         "replicas-max override per tier).  Unset = "
                         "fixed-size fleet")
    ap.add_argument("--autoscale-interval", type=float, default=1.0,
                    help="autoscaler tick cadence in seconds")
    ap.add_argument("--autoscale-up-load", type=float, default=0.8,
                    help="scale up when committed-token load (fraction "
                         "of aggregate KV capacity) stays above this")
    ap.add_argument("--autoscale-down-load", type=float, default=0.25,
                    help="scale down when load stays below this")
    ap.add_argument("--autoscale-hysteresis", type=int, default=3,
                    help="consecutive ticks past a threshold before the "
                         "autoscaler acts (noise filter)")
    ap.add_argument("--autoscale-cooldown", type=float, default=10.0,
                    help="quiet seconds after every scale action (or "
                         "failed attempt) before the next one")
    ap.add_argument("--tenant-weights", default=None,
                    help="multi-tenant weighted-fair serving: "
                         "\"gold:4,free:1\"-style shares (\"*\" sets the "
                         "default weight).  Requests carry X-Tenant (or "
                         "a \"tenant\" body field); admission serves "
                         "tenants by virtual token counter — a flooding "
                         "tenant cannot crowd out a lighter one's share "
                         "(default: runtime.tenant_weights)")
    ap.add_argument("--tenant-quota-tps", type=float, default=None,
                    help="per-tenant token-rate quota at the gateway: "
                         "admitted prompt+budget tokens/s per unit "
                         "weight; a tenant over its rate sheds 429 with "
                         "its OWN Retry-After (0 disables; default: "
                         "runtime.tenant_quota_tps)")
    ap.add_argument("--tenant-max-rows", type=int, default=None,
                    help="per-tenant resident-row cap in the batcher: a "
                         "tenant at the cap defers admission while "
                         "others admit past it (0 = uncapped; default: "
                         "runtime.tenant_max_rows)")
    ap.add_argument("--probe-interval", type=float, default=0.25,
                    help="fleet health-probe interval in seconds "
                         "(replica /healthz polling cadence)")
    ap.add_argument("--failover-retries", type=int, default=2,
                    help="router failover budget: how many other replicas "
                         "a zero-streamed request may be re-sent to after "
                         "a replica failure before answering 503 + "
                         "Retry-After")
    ap.add_argument("--max-pending", type=int, default=256,
                    help="in-flight request cap before 429s")
    ap.add_argument("--drain-timeout", type=float, default=30.0,
                    help="graceful shutdown: seconds to let in-flight "
                         "requests finish before cancelling (0 = immediate)")
    ap.add_argument("--request-timeout", type=float, default=None,
                    help="default per-request deadline in seconds: an "
                         "expired request cancels at the next chunk and "
                         "returns finish_reason \"timeout\" with its "
                         "partial output; a request's own timeout_s field "
                         "wins (default: runtime.request_timeout_s)")
    ap.add_argument("--shed-cost-factor", type=float, default=None,
                    help="estimated-cost admission gate: 429 (with "
                         "Retry-After) once queued + resident token mass "
                         "exceeds this multiple of KV capacity — overload "
                         "sheds at the front door instead of queueing "
                         "doomed work (0 disables; default: "
                         "runtime.shed_cost_factor)")
    ap.add_argument("--constrained", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="grammar-constrained structured output: the "
                         "response_format={\"type\": \"json_schema\"|"
                         "\"regex\"} request fields plus logit_bias / "
                         "banned_tokens, served as token-mask automata "
                         "fused into the shared decode step.  "
                         "--no-constrained answers every constrained "
                         "request 400 (default: "
                         "runtime.constrained_decoding, on)")
    ap.add_argument("--spec-decode", action=argparse.BooleanOptionalAction,
                    default=None,
                    help="speculative decoding: the engine drafts spec-k "
                         "tokens per row with its own int-quantized "
                         "self-draft and verifies them in one target "
                         "forward — temp-0 bytes identical with it on or "
                         "off.  Composes with --paged-pages (the "
                         "draft/verify window writes through the page "
                         "tables), --prefix-cache, --kv-bits 8, and the "
                         "host tier; rejected with --prefill-chunk and "
                         "on meshes (default: runtime.spec_decode)")
    ap.add_argument("--spec-k", type=int, default=None,
                    help="draft tokens per speculative round "
                         "(default: runtime.spec_k)")
    ap.add_argument("--spec-adaptive-k",
                    action=argparse.BooleanOptionalAction, default=None,
                    help="adaptive per-row spec_k downshift from the "
                         "acceptance-rate EMA + token budget "
                         "(default: runtime.spec_adaptive_k)")
    ap.add_argument("--constrain-cache", type=int, default=None,
                    help="LRU capacity of the compiled (constraint, "
                         "tokenizer) automaton cache (default: "
                         "runtime.constrain_cache_size)")
    ap.add_argument("--watchdog-timeout", type=float, default=30.0,
                    help="engine watchdog: /healthz flips unhealthy when "
                         "in-flight work exists but no chunk was delivered "
                         "for this many seconds")
    ap.add_argument("--fault", action="append", default=[],
                    help="deterministic fault injection spec "
                         "(runtime/faults.py grammar, repeatable): e.g. "
                         "'batcher.decode:raise@3' crashes the 3rd decode "
                         "chunk, 'batcher.page_alloc:exhaust@1+' dries the "
                         "KV pool, 'batcher.decode:stall@2:1.5' wedges a "
                         "chunk for the watchdog.  Operator drills / CI "
                         "only — the supervisor restart is the tested path")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu) — the axon TPU "
                         "plugin ignores JAX_PLATFORMS, so this sets "
                         "jax.config before backend init")
    args = ap.parse_args(argv)
    if args.replicas_max is not None and args.replicas_max < 1:
        raise SystemExit(f"--replicas-max must be >= 1, got "
                         f"{args.replicas_max}")
    for k in ("prefill_replicas_max", "decode_replicas_max"):
        v = getattr(args, k)
        flag = f"--{k.replace('_', '-')}"
        if v is not None and v < 1:
            raise SystemExit(f"{flag} must be >= 1, got {v}")
        if v is not None and not args.disaggregate:
            # Tier ceilings without tiers is config drift — reject in
            # milliseconds, before the model loads.
            raise SystemExit(f"{flag} needs --disaggregate")
    if args.replicas_max is None and args.prefill_replicas_max is None \
            and args.decode_replicas_max is None:
        # A max ceiling is THE elastic-fleet switch: the floor and every
        # autoscale knob mean nothing without one — reject loudly instead
        # of booting a fixed fleet the operator believes is elastic.
        stray = [f"--{k.replace('_', '-')}" for k in (
            "replicas_min", "autoscale_interval", "autoscale_up_load",
            "autoscale_down_load", "autoscale_hysteresis",
            "autoscale_cooldown",
        ) if getattr(args, k) != ap.get_default(k)]
        if stray:
            raise SystemExit(
                f"{', '.join(stray)} need --replicas-max (or a "
                "--prefill/--decode-replicas-max tier ceiling)"
            )
    if args.disaggregate and args.replicas_min != ap.get_default(
            "replicas_min"):
        raise SystemExit(
            "--replicas-min sizes the colocated elastic fleet; "
            "--disaggregate tiers floor at --prefill-replicas/"
            "--decode-replicas"
        )
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    asyncio.run(_serve(args))


if __name__ == "__main__":
    main()
