"""Coordinator CLI: REPL parity with the reference's run_master.py.

Reference REPL (run_master.py:28-42): assign / distribute / inference / exit.
Here (same verbs kept, mesh semantics):
  init <model_id_or_path> [num_shards]  - fetch + convert + write shard store
                                          (initialize_model parity, :54-82)
  assign [num_shards]                   - plan shard->worker assignment
  distribute                            - workers load their shards (place)
  inference                             - prompt for text, generate, print
  status / metrics                      - registry + counters
  exit
Plus ``--local N``: spawn N in-process workers (the reference's planned
multiprocessing local-simulation mode, snippets.md:835-846 / plan.md:225-233,
which never landed).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ..checkpoint import convert, store
from ..checkpoint.download import fetch_model
from ..cluster.coordinator import Coordinator
from ..cluster.worker import WorkerHost
from ..core.config import Config, load_config
from ..core.observability import METRICS, get_logger

log = get_logger("cli")


async def _ainput(prompt: str) -> str:
    return await asyncio.to_thread(input, prompt)


def init_store(model_id: str, num_shards: int, cfg: Config) -> str:
    """Fetch checkpoint, convert to param tree, write the shard store."""
    local = fetch_model(model_id, cache_dir=cfg.checkpoint.cache_dir)
    import os

    with open(os.path.join(local, "config.json")) as f:
        model_cfg = convert.config_from_hf(json.load(f))
    params = convert.convert_state_dict(convert.load_state_dict(local), model_cfg)
    out_dir = cfg.checkpoint.shard_dir
    store.save_shards(
        params, out_dir, num_shards=num_shards, model_config=model_cfg,
        quantization=cfg.checkpoint.quantization,
        quant_block=cfg.checkpoint.quant_block_size,
        tokenizer_src=local,  # ship the model's own tokenizer with the store
    )
    print(f"sharded {model_id} -> {out_dir} ({num_shards} shards)")
    return out_dir


async def repl(coord: Coordinator, cfg: Config) -> None:
    print("commands: init <model> [shards] | assign [shards] [policy] | "
          "distribute | rebalance | inference | batch | status | metrics | exit")
    store_dir: str | None = None
    while True:
        try:
            line = (await _ainput("> ")).strip()
        except (EOFError, KeyboardInterrupt):
            break
        if not line:
            continue
        cmd, *rest = line.split()
        try:
            if cmd == "init":
                model_id = rest[0] if rest else cfg.model_id
                shards = int(rest[1]) if len(rest) > 1 else cfg.checkpoint.num_shards
                store_dir = init_store(model_id, shards, cfg)
            elif cmd == "assign":
                shards = int(rest[0]) if rest else cfg.checkpoint.num_shards
                policy = rest[1] if len(rest) > 1 else "capacity"
                plan = coord.plan_shards(
                    shards, store_dir=store_dir or cfg.checkpoint.shard_dir,
                    policy=policy,
                )
                print(json.dumps({str(k): v for k, v in plan.items()}, indent=1))
            elif cmd == "distribute":
                print(json.dumps(await coord.place_shards(), indent=1))
            elif cmd == "rebalance":
                plan = await coord.rebalance()
                print(json.dumps({str(k): v for k, v in plan.items()}, indent=1))
            elif cmd == "inference":
                text = await _ainput("prompt: ")
                out = await coord.generate([text])
                print(out["text"][0])
                print(f"[{out['generated_tokens']} tokens, "
                      f"{out['tokens_per_second']:.1f} tok/s]")
            elif cmd == "batch":
                # Mixed-budget batch: N lines of "<max_new_tokens> <prompt>",
                # blank line ends; served via continuous batching.
                print("one request per line: <max_new_tokens> <prompt>; "
                      "blank line runs the batch")
                reqs = []
                while True:
                    line2 = (await _ainput("req: ")).strip()
                    if not line2:
                        break
                    n_str, _, ptext = line2.partition(" ")
                    try:
                        n_new = int(n_str)
                    except ValueError:
                        n_new = 0
                    if n_new < 1 or not ptext.strip():
                        # Don't let one malformed line discard the batch.
                        print(f"expected '<max_new_tokens> <prompt>' with a "
                              f"positive budget, got {line2!r}; line skipped")
                        continue
                    reqs.append({"prompt": ptext, "max_new_tokens": n_new})
                if reqs:
                    out = await coord.generate_requests(reqs)
                    for i, t in enumerate(out["text"]):
                        print(f"[{i}] {t}")
                    print(f"[{out['generated_tokens']} tokens, "
                          f"{out['tokens_per_second']:.1f} tok/s]")
            elif cmd == "status":
                print(json.dumps(coord.status(), indent=1))
            elif cmd == "metrics":
                print(json.dumps(METRICS.snapshot(), indent=1))
            elif cmd in ("exit", "quit"):
                break
            else:
                print(f"unknown command {cmd!r}")
        except Exception as e:
            print(f"error: {e}")


async def amain(args: argparse.Namespace) -> None:
    import dataclasses

    cfg = load_config(args.config, args.override)
    ccfg = dataclasses.replace(
        cfg.cluster,
        coordinator_host=args.host or cfg.cluster.coordinator_host,
        coordinator_port=args.port if args.port is not None else cfg.cluster.coordinator_port,
        metrics_port=args.metrics_port if args.metrics_port is not None
        else cfg.cluster.metrics_port,
    )
    coord = Coordinator(ccfg)
    await coord.start()
    local_tasks = []
    procs = []
    if args.local:
        rt = cfg.runtime
        for _ in range(args.local):
            w = WorkerHost("127.0.0.1", coord.port, cfg=ccfg, rt=rt, mesh_cfg=cfg.mesh)
            local_tasks.append(asyncio.create_task(w.run()))
        log.info("spawned %d local in-process workers", args.local)
    if args.local_proc:
        # True process isolation (the reference's planned multiprocessing
        # local-simulation mode, plan.md:225-233): each worker is a separate
        # interpreter running the host entry point.
        import subprocess

        for i in range(args.local_proc):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "distributed_llms_tpu.cli.host_main",
                 "--host", "127.0.0.1", "--port", str(coord.port),
                 *(["--platform", args.platform] if args.platform else []),
                 *(["--config", args.config] if args.config else []),
                 *(x for ov in args.override for x in ("--override", ov))],
            ))
        log.info("spawned %d local worker processes", args.local_proc)
    expected = args.local + args.local_proc
    if expected:
        # Don't hand the REPL to the user (or a piped script) until the local
        # workers are actually registered — otherwise the first `assign`
        # races the registrations.
        for _ in range(600):
            if len(coord.workers) >= expected:
                break
            await asyncio.sleep(0.1)
        else:
            log.warning(
                "only %d/%d local workers registered", len(coord.workers), expected
            )
    try:
        if args.serve:
            # Headless daemon mode: containers/K8s have no interactive
            # stdin, and a REPL there would hit EOF and exit immediately.
            # As PID 1, Python's default SIGTERM action would kill the
            # interpreter before the finally-cleanup runs; catch it.
            import signal

            stop = asyncio.Event()
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGTERM, signal.SIGINT):
                loop.add_signal_handler(sig, stop.set)
            log.info("serving headless (no REPL); SIGTERM/Ctrl-C stops")
            await stop.wait()
            log.info("stop signal received; shutting down")
        else:
            await repl(coord, cfg)
    finally:
        for t in local_tasks:
            t.cancel()
        for p in procs:
            p.terminate()
        await coord.stop()


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="distributed-llms-tpu coordinator")
    ap.add_argument("--config", default=None, help="JSON/YAML config file")
    ap.add_argument("--override", action="append", default=[], metavar="K=V",
                    help="dotted config override, e.g. mesh.pipe=2")
    ap.add_argument("--host", default=None)
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus /metrics (+/healthz, /status) here")
    ap.add_argument("--serve", action="store_true",
                    help="headless daemon mode (no REPL) — for containers/K8s "
                         "where stdin is closed; default is the interactive "
                         "REPL, which also accepts piped command scripts")
    ap.add_argument("--local", type=int, default=0, metavar="N",
                    help="spawn N in-process workers (local simulation)")
    ap.add_argument("--local-proc", type=int, default=0, metavar="N",
                    help="spawn N worker *processes* (isolated local simulation)")
    ap.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                    help="force a JAX platform (e.g. cpu for a CPU-only host)")
    args = ap.parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        sys.exit(0)


if __name__ == "__main__":
    main()
