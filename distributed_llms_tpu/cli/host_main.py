"""Host-runner CLI: parity with the reference's run_worker.py (:12-23) —
connect to the coordinator, serve commands, clean stop on Ctrl-C."""

from __future__ import annotations

import argparse
import asyncio
import os
import sys

from ..cluster.distributed import initialize_distributed
from ..cluster.worker import WorkerHost
from ..core.config import load_config


async def amain(args: argparse.Namespace) -> None:
    cfg = load_config(args.config, args.override)
    initialize_distributed(cfg.cluster)
    # CLI flags win when given; otherwise the config file decides.
    host = args.host if args.host is not None else cfg.cluster.coordinator_host
    port = args.port if args.port is not None else cfg.cluster.coordinator_port
    if host == "0.0.0.0":  # bind-any is not a connect address
        host = "localhost"
    worker = WorkerHost(host, port, cfg=cfg.cluster, rt=cfg.runtime, mesh_cfg=cfg.mesh)
    if args.worker_id:
        # Stable identity across restarts (e.g. the StatefulSet pod name):
        # the coordinator re-registers the same id, so shard assignment and
        # pinned tasks survive a host bounce.
        worker.worker_id = args.worker_id
    await worker.run()


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="distributed-llms-tpu host runner")
    ap.add_argument("--config", default=None)
    ap.add_argument("--override", action="append", default=[], metavar="K=V")
    ap.add_argument("--host", default=None,
                    help="coordinator host (default: from config)")
    ap.add_argument("--port", type=int, default=None,
                    help="coordinator port (default: from config)")
    ap.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                    help="force a JAX platform (e.g. cpu for a CPU-only host)")
    ap.add_argument("--worker-id", default=os.environ.get("DLT_WORKER_ID"),
                    help="stable worker identity to register under (default: "
                         "$DLT_WORKER_ID; unset -> coordinator assigns one)")
    args = ap.parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        print("stopping worker")
        sys.exit(0)


if __name__ == "__main__":
    main()
