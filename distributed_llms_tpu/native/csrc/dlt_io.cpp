// Native IO tier for the shard store (checkpoint/store.py).
//
// The reference has no native components at all (SURVEY: 100% Python), but a
// real framework's checkpoint path is IO-bound at cold start: loading a 7B
// bf16 model is ~14 GB of disk reads.  This library does the store reads the
// way a C++ runtime would:
//   - per-tensor pread() segments fanned out over a thread pool (no GIL, no
//     Python object churn, page-cache friendly);
//   - CRC32 (zlib polynomial, slice-by-8) computed in the same pass for
//     integrity checking — corruption surfaces as a checksum mismatch at
//     load time, not NaNs at step 40k.
//
// Exposed as a plain C ABI consumed via ctypes (pybind11 is not in the
// image; ctypes keeps the build a single `g++ -shared`).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

uint32_t crc_table[8][256];

void init_crc_tables() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int s = 1; s < 8; s++)
      crc_table[s][i] =
          crc_table[0][crc_table[s - 1][i] & 0xFF] ^ (crc_table[s - 1][i] >> 8);
}

struct CrcInit {
  CrcInit() { init_crc_tables(); }
} crc_init;

uint32_t crc32_update(uint32_t crc, const uint8_t* p, uint64_t len) {
  crc = ~crc;
  // slice-by-8
  while (len >= 8) {
    uint32_t lo;
    uint32_t hi;
    memcpy(&lo, p, 4);
    memcpy(&hi, p + 4, 4);
    lo ^= crc;
    crc = crc_table[7][lo & 0xFF] ^ crc_table[6][(lo >> 8) & 0xFF] ^
          crc_table[5][(lo >> 16) & 0xFF] ^ crc_table[4][lo >> 24] ^
          crc_table[3][hi & 0xFF] ^ crc_table[2][(hi >> 8) & 0xFF] ^
          crc_table[1][(hi >> 16) & 0xFF] ^ crc_table[0][hi >> 24];
    p += 8;
    len -= 8;
  }
  while (len--) crc = crc_table[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

}  // namespace

extern "C" {

uint32_t dlt_crc32(const uint8_t* data, uint64_t len, uint32_t seed) {
  return crc32_update(seed, data, len);
}

// Read `count` segments — paths[i] at byte offsets[i], nbytes[i] bytes —
// into caller-owned bufs[i], optionally writing CRC32s to crcs_out.
// Returns 0 on success, or (1 + i) for the first segment that failed.
int64_t dlt_read_segments(const char** paths, const uint64_t* offsets,
                          const uint64_t* nbytes, uint8_t** bufs,
                          uint32_t* crcs_out, int64_t count, int threads) {
  if (count <= 0) return 0;
  if (threads < 1) threads = 1;
  if (threads > count) threads = static_cast<int>(count);

  std::atomic<int64_t> next(0);
  std::atomic<int64_t> failed(0);  // 0 = ok, else 1-based segment index

  auto worker = [&]() {
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= count || failed.load(std::memory_order_relaxed)) break;
      int fd = open(paths[i], O_RDONLY);
      if (fd < 0) {
        failed.store(i + 1);
        break;
      }
      uint64_t done = 0;
      bool ok = true;
      while (done < nbytes[i]) {
        ssize_t r = pread(fd, bufs[i] + done, nbytes[i] - done,
                          static_cast<off_t>(offsets[i] + done));
        if (r <= 0) {
          ok = false;
          break;
        }
        done += static_cast<uint64_t>(r);
      }
      close(fd);
      if (!ok) {
        failed.store(i + 1);
        break;
      }
      if (crcs_out) crcs_out[i] = crc32_update(0, bufs[i], nbytes[i]);
    }
  };

  std::vector<std::thread> pool;
  pool.reserve(threads);
  for (int t = 0; t < threads; t++) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
  return failed.load();
}

}  // extern "C"
