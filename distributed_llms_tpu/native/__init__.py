"""Native (C++) IO tier: build-on-first-use loader + ctypes bindings.

The reference is 100% Python (SURVEY §2 intro — no native components to
port), so this tier exists where native code actually pays on TPU hosts: the
checkpoint cold-load path.  ``read_segments`` fans per-tensor ``pread``s
over a C++ thread pool with CRC32 integrity computed in-pass; the pure-
Python fallback keeps every caller working when no compiler is available.

Build model: single-file ``g++ -O3 -shared`` compiled lazily into
``_cache/`` next to the source (rebuilt when the source is newer), no
setuptools/pybind11 dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import zlib
from typing import Sequence

import numpy as np

from ..core.observability import get_logger

log = get_logger("native")

_SRC = os.path.join(os.path.dirname(__file__), "csrc", "dlt_io.cpp")
_CACHE = os.path.join(os.path.dirname(__file__), "_cache")
_SO = os.path.join(_CACHE, "dlt_io.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> str | None:
    os.makedirs(_CACHE, exist_ok=True)
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    # Per-process temp name: concurrent cold-start builds (e.g. the
    # process-isolated local sim spawning N workers) must not interleave
    # writes; os.replace makes the final install atomic either way.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", b"") or b""
        log.warning("native build failed (%s); using Python IO fallback: %s",
                    e, detail.decode(errors="replace")[:500])
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def get_lib() -> ctypes.CDLL | None:
    """The compiled library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            log.warning("loading %s failed (%s); using Python IO fallback", so, e)
            return None
        lib.dlt_crc32.restype = ctypes.c_uint32
        lib.dlt_crc32.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
        ]
        lib.dlt_read_segments.restype = ctypes.c_int64
        lib.dlt_read_segments.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int64,
            ctypes.c_int,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def read_segments(
    tasks: Sequence[tuple[str, int, int]],  # (path, offset, nbytes)
    threads: int = 8,
    with_crc: bool = True,
) -> tuple[list[np.ndarray], list[int] | None]:
    """Read byte segments (parallel native pread when available, Python
    fallback otherwise).  Returns (uint8 buffers, crc32s or None)."""
    lib = get_lib()
    if lib is None:
        return _read_segments_py(tasks, with_crc)
    n = len(tasks)
    bufs = [np.empty(nb, dtype=np.uint8) for _, _, nb in tasks]
    paths = (ctypes.c_char_p * n)(*(p.encode() for p, _, _ in tasks))
    offs = (ctypes.c_uint64 * n)(*(o for _, o, _ in tasks))
    sizes = (ctypes.c_uint64 * n)(*(nb for _, _, nb in tasks))
    ptrs = (ctypes.c_void_p * n)(*(b.ctypes.data for b in bufs))
    crcs = (ctypes.c_uint32 * n)() if with_crc else None
    rc = lib.dlt_read_segments(
        paths, offs, sizes, ptrs,
        crcs if with_crc else ctypes.cast(None, ctypes.POINTER(ctypes.c_uint32)),
        n, threads,
    )
    if rc != 0:
        i = int(rc) - 1
        raise IOError(f"native read failed for {tasks[i][0]} @ {tasks[i][1]}")
    return bufs, (list(crcs) if with_crc else None)


def _read_segments_py(
    tasks: Sequence[tuple[str, int, int]], with_crc: bool
) -> tuple[list[np.ndarray], list[int] | None]:
    bufs: list[np.ndarray] = []
    crcs: list[int] | None = [] if with_crc else None
    for path, off, nb in tasks:
        with open(path, "rb") as f:
            f.seek(off)
            data = f.read(nb)
        if len(data) != nb:
            raise IOError(f"short read from {path} @ {off} ({len(data)}/{nb})")
        buf = np.frombuffer(data, dtype=np.uint8)
        bufs.append(buf)
        if with_crc:
            crcs.append(zlib.crc32(data) & 0xFFFFFFFF)
    return bufs, crcs


def crc32(data: bytes | np.ndarray) -> int:
    """CRC32 via the native library when present (zlib fallback — identical
    polynomial, so stores written either way verify either way).  ndarray
    input is checksummed in place, no bytes copy."""
    lib = get_lib()
    if isinstance(data, np.ndarray):
        arr = np.ascontiguousarray(data)
        if lib is None:
            return zlib.crc32(arr.view(np.uint8).reshape(-1)) & 0xFFFFFFFF
        return int(
            lib.dlt_crc32(arr.ctypes.data_as(ctypes.c_char_p), arr.nbytes, 0)
        )
    if lib is None:
        return zlib.crc32(data) & 0xFFFFFFFF
    return int(lib.dlt_crc32(data, len(data), 0))
