#!/usr/bin/env python
"""Regenerate BASELINE.md's ladder-of-record section from BENCH_LADDER.json.

VERDICT r3 weak #2 / next-step 8: BASELINE.md's performance claims must come
from the measured artifact, not hand-maintained prose — a config that is
merely *instrumented* must read NOT YET MEASURED until a row with a
``measured_on`` stamp exists.  This script rewrites everything between the
AUTOGEN markers in BASELINE.md from the JSON; run it after every ladder run
(tools/tpu_runbook.sh reminds you).
"""
from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BEGIN = "<!-- BEGIN AUTOGEN LADDER (tools/gen_baseline.py) -->"
END = "<!-- END AUTOGEN LADDER -->"


def _result_cell(row: dict) -> str:
    if "skipped" in row:
        return f"SKIPPED — {row['skipped']}"
    cells = []
    if "tok_per_s" in row:
        cells.append(f"**{row['tok_per_s']:.1f} tok/s**")
    for k, label in (
        ("mfu_2N", "MFU_2N"), ("hbm_util", "hbm_util"),
        ("weight_stream_gb_per_s", "weight-stream GB/s"),
        ("ttft_p50_ms", "TTFT p50 ms"), ("ttft_p95_ms", "TTFT p95 ms"),
        ("tpot_ms", "TPOT ms"), ("tok_per_s_steady", "steady tok/s"),
        ("tok_per_s_continuous", "continuous tok/s"),
        ("tok_per_s_grouped", "grouped tok/s"),
        ("tok_per_s_paged", "paged tok/s"),
        ("tok_per_s_contiguous", "contiguous tok/s"),
        ("kv_memory_ratio", "paged/contiguous KV bytes"),
        ("dense_chunk_ms", "dense ms"), ("ragged_chunk_ms", "ragged ms"),
        ("speedup", "speedup"),
        ("flash_ms", "flash ms"), ("dot_ms", "dot ms"),
        ("p50_us", "p50 µs"), ("p95_us", "p95 µs"),
        ("tok_per_s_end_to_end", "end-to-end tok/s"),
        ("tok_per_s_in_engine", "in-engine tok/s"),
        ("cluster_overhead_pct", "cluster overhead %"),
        ("rtt_1tok_p50_ms", "1-tok RTT p50 ms"),
        ("short_done_ms_monolithic", "short-req ms (monolithic)"),
        ("short_done_ms_chunked", "short-req ms (chunked)"),
        ("ttft_ms_cache_off", "TTFT ms cache-off"),
        ("ttft_ms_cache_on", "TTFT ms cache-on"),
        ("ttft_ms_shared_off", "shared-prefix TTFT ms off"),
        ("ttft_ms_shared_on", "shared-prefix TTFT ms on"),
        ("prefill_tokens_saved", "prefill tokens saved"),
        ("hit_rate", "hit rate"),
        ("recovery_ms", "recovery ms"),
        ("completed_frac", "completed frac"),
        ("engine_restarts", "engine restarts"),
        ("requests_retried", "requests retried"),
        ("replicas", "replicas"),
        ("exact", "byte-exact"),
        ("failovers", "failovers"),
        ("short_ms_colocated", "short-req ms (colocated)"),
        ("short_ms_disagg", "short-req ms (disagg)"),
        ("interference_speedup", "interference speedup"),
        ("handoff_ms_p50", "handoff p50 ms"),
        ("fallback_recovery_ms", "prefill-kill fallback ms"),
        ("goodput_tok_per_s", "goodput tok/s"),
        ("aggressor_offered_x", "aggressor offered x quota"),
        ("victim_goodput_off", "victim goodput tok/s (QoS off)"),
        ("victim_goodput_on", "victim goodput tok/s (QoS on)"),
        ("victim_goodput_gain", "victim goodput gain x"),
        ("victim_slo_off", "victim SLO attainment (off)"),
        ("victim_slo_on", "victim SLO attainment (on)"),
        ("victim_itl_p95_ms_off", "victim ITL p95 ms (off)"),
        ("victim_itl_p95_ms_on", "victim ITL p95 ms (on)"),
        ("aggressor_shed_frac", "aggressor shed frac"),
        ("scale_up_s", "scale-up s"),
        ("scale_down_s", "scale-down s"),
        ("goodput_tok_per_s_colocated", "goodput tok/s (colocated)"),
        ("goodput_tok_per_s_disagg", "goodput tok/s (disagg)"),
        ("exact_disagg", "byte-exact (disagg)"),
        ("handoffs", "handoffs"),
        ("directory_hit_rate", "directory hit rate"),
        ("pulled_pages", "pages pulled"),
        ("pull_fallbacks", "pull fallbacks"),
        ("pull_ttft_ms", "pull TTFT ms"),
        ("reprefill_ttft_ms", "re-prefill TTFT ms"),
        ("pull_ttft_speedup", "pull TTFT speedup"),
        ("offered_x", "offered load x"),
        ("shed_frac", "shed frac"),
        ("preemptions", "preemptions"),
        ("rows_bf16", "rows @bf16"),
        ("rows_int8", "rows @int8"),
        ("capacity_factor_int8", "int8 capacity factor"),
        ("swap_restore_ms", "swap restore ms"),
        ("recompute_restore_ms", "recompute restore ms"),
        ("swap_speedup", "swap speedup"),
        ("spill_hit_ttft_ms", "spill-hit TTFT ms"),
        ("cold_ttft_ms", "cold TTFT ms"),
        ("rows_per_chip_tp1", "rows/chip @tp1"),
        ("rows_per_chip_tp2", "rows/chip @tp2"),
        ("capacity_factor_tp2", "tp2 capacity factor"),
        ("tok_per_s_tp1", "tok/s @tp1"),
        ("tok_per_s_tp2", "tok/s @tp2"),
        ("per_chip_pool_kb", "per-chip pool KB"),
        ("tok_per_s_overlap_off", "tok/s overlap-off"),
        ("tok_per_s_overlap_on", "tok/s overlap-on"),
        ("dfa_compile_ms", "DFA compile ms"),
        ("tok_per_s_free", "free tok/s"),
        ("tok_per_s_constrained", "constrained tok/s"),
        ("mask_overhead_pct", "mask overhead %"),
        ("parse_valid_frac", "parse-valid frac"),
        ("device_gap_ms_off", "device-gap ms off"),
        ("device_gap_ms_on", "device-gap ms on"),
        ("gap_reduction", "gap reduction x"),
        ("dispatched_ahead_frac", "dispatched-ahead frac"),
        ("exact_spec_vs_plain", "spec byte-exact"),
        ("tok_per_s_plain", "tok/s spec-off"),
        ("tok_per_s_spec", "tok/s spec-on"),
        ("itl_p50_ms_plain", "ITL p50 ms spec-off"),
        ("itl_p50_ms_spec", "ITL p50 ms spec-on"),
        ("acceptance_frac", "acceptance frac"),
        ("spec_rounds", "spec rounds"),
        ("k_downshifts", "k downshifts"),
        ("rows_contig_spec", "rows @contiguous-spec"),
        ("rows_paged_spec", "rows @paged-spec"),
        ("capacity_factor", "capacity factor"),
        ("pool_kib", "pool KiB"),
        ("itl_p95_ms_alternate", "ITL p95 ms (alternate)"),
        ("itl_p95_ms_mixed", "ITL p95 ms (mixed)"),
        ("itl_p95_gain", "ITL p95 gain x"),
        ("ttft_first_s_alternate", "long-prompt TTFT s (alternate)"),
        ("ttft_first_s_mixed", "long-prompt TTFT s (mixed)"),
        ("ttft_ratio", "TTFT ratio (mixed/alternate)"),
        ("ttft_last_s_mixed", "last-prefill TTFT s (mixed)"),
        ("stall_rounds_alternate", "stall bites (alternate)"),
        ("stall_rounds_mixed", "stall bites (mixed)"),
        ("admit_row_keys", "admit compile keys"),
        ("admit_row_declared", "of declared"),
        ("decode_chunk_keys", "decode compile keys"),
        ("decode_chunk_declared", "of declared"),
        ("decode_chunk_overlap_keys", "overlap decode compile keys"),
        ("decode_chunk_overlap_declared", "of declared"),
        ("decode_chunk_constrained_keys", "constrained decode compile keys"),
        ("decode_chunk_constrained_declared", "of declared"),
        ("generate_tokens_keys", "generate compile keys"),
        ("generate_tokens_declared", "of declared"),
        ("trace_wall_ms", "trace wall ms"),
        ("graftlint_wall_ms", "graftlint ms"),
        ("graftcheck_wall_ms", "graftcheck ms"),
        ("graftflow_wall_ms", "graftflow ms"),
        ("graftsync_wall_ms", "graftsync ms"),
        ("graftmodel_wall_ms", "graftmodel ms"),
        ("analysis_wall_ms", "combined analysis ms"),
    ):
        if row.get(k) is not None:
            v = row[k]
            cells.append(f"{label} {v:.3g}" if isinstance(v, float) else f"{label} {v}")
    if row.get("degraded"):
        cells.append(f"DEGRADED: {row['degraded']}")
    return ", ".join(cells) or json.dumps(
        {k: v for k, v in row.items() if k not in ("config", "measured_on")}
    )[:120]


def generate(ladder_path: str) -> str:
    import bench  # repo-root bench.py — the ladder definition of record

    with open(ladder_path) as f:
        rows = {str(r.get("config")): r for r in json.load(f)["rows"]}
    lines = [
        BEGIN,
        "",
        "## Ladder of record (auto-generated from BENCH_LADDER.json)",
        "",
        "A config with no `measured on` stamp has **never produced a "
        "number** — treat every claim about it as design intent, not data.",
        "",
        "| Config | Preset | Result | Measured on |",
        "|--------|--------|--------|-------------|",
    ]
    listed = [str(e["config"]) for e in bench.LADDER] + [
        # Aux rows run_ladder appends after the decode configs.
        "serving-latency", "continuous-batching", "local-proc-batching",
        "chunked-prefill", "prefix-cache-ttft", "fault-recovery",
        "overload-goodput", "tenant-qos", "kv-tiering", "decode-overlap",
        "mixed-step", "spec-paged",
        "constrained-decode", "mesh-paged", "replica-failover",
        "fleet-goodput", "disagg-handoff", "compile-stability",
        "analysis-wall",
        "ragged-decode-8k", "ragged-decode-win-8k", "quant-matmul-bw",
        "spec-decode", "spec-decode-7b-int8", "spec-batching",
        "paged-batching", "prefill-flash-2048", "prefill-flash-8192",
        "prefill-flash-win-8192", "hop-latency",
    ]
    extras = [c for c in rows if c not in listed]
    for cfg_id in listed + extras:
        row = rows.get(cfg_id)
        entry = next(
            (e for e in bench.LADDER if str(e["config"]) == cfg_id), {}
        )
        preset = (row or {}).get("preset", entry.get("preset", "—"))
        if row is None:
            lines.append(
                f"| {cfg_id} | {preset} | NOT YET MEASURED (instrumented in "
                f"bench.py; no row in the artifact) | — |"
            )
            continue
        stamp = row.get("measured_on", "pre-r4 artifact (no stamp)")
        if "skipped" in row:
            stamp = "—"
        lines.append(f"| {cfg_id} | {preset} | {_result_cell(row)} | {stamp} |")
    lines += ["", END]
    return "\n".join(lines)


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ladder = os.path.join(repo, "BENCH_LADDER.json")
    baseline = os.path.join(repo, "BASELINE.md")
    section = generate(ladder)
    with open(baseline) as f:
        text = f.read()
    if BEGIN in text and END in text:
        pattern = re.escape(BEGIN) + r".*?" + re.escape(END)
        text = re.sub(pattern, lambda _m: section, text, flags=re.DOTALL)
    else:
        text = text.rstrip() + "\n\n" + section + "\n"
    with open(baseline, "w") as f:
        f.write(text)
    print(f"BASELINE.md ladder section regenerated from {ladder}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
