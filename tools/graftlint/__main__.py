"""CLI: ``python -m tools.graftlint [--root DIR]``.

Exit status: 0 when every finding is either absent or baselined, 1 when
NEW findings exist (the tier-1 gate mirrors this via
tests/tools/test_graftlint.py), 2 on usage errors.

- ``--baseline-write``: accept the current findings as debt (rewrites
  ``graftlint_baseline.txt`` with normalized, line-number-free entries).
- ``--write-docs``: regenerate the README fault-site/metric tables from
  the code registries (the GL304 drift check compares against these).
- ``--all``: print baselined findings too (marked), not just new ones.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from . import (load_project, read_baseline, run_project, split_new,
               write_baseline)
from .registry import write_docs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="repo-native static analysis (see tools/graftlint/)",
    )
    ap.add_argument("--root", default=".", help="repo root to analyze")
    ap.add_argument("--baseline-write", action="store_true",
                    help="accept current findings into the baseline")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate README registry tables, then exit")
    ap.add_argument("--all", action="store_true",
                    help="also print baselined (accepted) findings")
    args = ap.parse_args(argv)

    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"graftlint: --root {root} is not a directory", file=sys.stderr)
        return 2
    project = load_project(root)

    if args.write_docs:
        done = write_docs(project)
        print(f"graftlint: rewrote README tables: {', '.join(done) or 'none'}")
        return 0

    findings = run_project(project)
    if args.baseline_write:
        path = write_baseline(root, findings)
        print(f"graftlint: wrote {len(findings)} finding(s) to {path.name}")
        return 0

    baseline = read_baseline(root)
    new, accepted = split_new(findings, baseline)
    for f in new:
        print(f.render())
    if args.all:
        for f in accepted:
            print(f"{f.render()}  [baselined]")
    from .core import stale_entries

    stale = stale_entries(findings, baseline)
    summary = (f"graftlint: {len(new)} new finding(s), "
               f"{len(accepted)} baselined, {len(stale)} stale baseline "
               f"entr{'y' if len(stale) == 1 else 'ies'}")
    print(summary, file=sys.stderr)
    if stale:
        print("graftlint: stale entries (fixed debt — run --baseline-write "
              "to shrink the baseline):", file=sys.stderr)
        for s in stale:
            print(f"  {s}", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
