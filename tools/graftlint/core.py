"""graftlint core: source loading, findings, suppressions, baseline.

graftlint is an AST-based, repo-specific static-analysis suite.  Each rule
module exposes ``check(project) -> list[Finding]``; this module owns the
shared plumbing:

- :class:`SourceFile`: parsed AST + per-line comment map (via ``tokenize``,
  so ``#`` inside string literals never reads as a comment);
- suppression comments
  (``# graftlint: unguarded-ok(<reason>)`` for the lock rule,
  ``# graftlint: ignore[RULE-ID](<reason>)`` for any rule,
  ``# graftlint: holds(<lock>)`` on a ``def`` asserting the caller holds
  the lock) — a suppression with an EMPTY reason is deliberately inert:
  accepted debt must say why;
- the checked-in baseline (``graftlint_baseline.txt``): findings are
  normalized WITHOUT line numbers (line churn must not resurrect debt)
  but WITH occurrence counts (``[xN]`` — one baselined occurrence must
  not absorb a newly added duplicate), and only findings beyond the
  baselined counts fail the gate.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path

BASELINE_NAME = "graftlint_baseline.txt"

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*"
    r"(?:(unguarded-ok)|ignore\[([A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*)\])"
    r"\(([^)]*)\)"
)
_HOLDS_RE = re.compile(r"#\s*graftlint:\s*holds\(([^)]+)\)")
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*(\S+)")


@dataclass(frozen=True)
class Finding:
    rule: str      # e.g. "GL101"
    path: str      # repo-relative posix path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def normalized(self) -> str:
        """Baseline key: no line number, so unrelated edits moving code
        up/down never turn accepted debt into a 'new' finding."""
        return f"{self.path}: {self.rule} {self.message}"


@dataclass
class SourceFile:
    path: Path                 # absolute
    rel: str                   # repo-relative posix
    text: str
    tree: ast.Module
    comments: dict[int, str] = field(default_factory=dict)  # line -> text
    lines: list[str] = field(default_factory=list)

    # -- comment-derived annotations ------------------------------------

    def _standalone_comment(self, line: int) -> bool:
        """Whether ``line`` is a comment-only line (a trailing comment on
        someone else's statement must never annotate the NEXT line)."""
        return (1 <= line <= len(self.lines)
                and self.lines[line - 1].lstrip().startswith("#"))

    def _comment_for(self, line: int) -> str:
        """Comments annotating ``line``: its own trailing comment plus a
        standalone comment line directly above."""
        own = self.comments.get(line, "")
        above = (self.comments.get(line - 1, "")
                 if self._standalone_comment(line - 1) else "")
        return f"{above}\n{own}"

    def suppressions(self, line: int) -> list[tuple[str | None, str]]:
        """(rule-or-None, reason) suppressions on ``line`` (or a
        standalone comment directly above it).  rule None means the
        lock-rule alias ``unguarded-ok``."""
        out: list[tuple[str | None, str]] = []
        for m in _SUPPRESS_RE.finditer(self._comment_for(line)):
            reason = m.group(3).strip()
            if not reason:
                continue  # reasonless suppressions don't count
            if m.group(1):
                out.append((None, reason))
            else:
                for rid in re.split(r"\s*,\s*", m.group(2)):
                    out.append((rid, reason))
        return out

    def suppressed(self, rule: str, line: int, lock_alias: bool = False) -> bool:
        for rid, _reason in self.suppressions(line):
            if rid == rule or (rid is None and lock_alias):
                return True
        return False

    def holds_locks(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
        """Locks a ``# graftlint: holds(<lock>)`` annotation asserts are
        held for the whole function (scanned from the first decorator line
        through the ``def`` line, plus the line above)."""
        first = min([fn.lineno] + [d.lineno for d in fn.decorator_list])
        out: set[str] = set()
        for ln in range(first - 1, fn.lineno + 1):
            for m in _HOLDS_RE.finditer(self.comments.get(ln, "")):
                out.add(normalize_expr(m.group(1)))
        return out

    def guarded_by(self, line: int) -> str | None:
        """The ``# guarded-by: <lock>`` annotation on ``line`` or on a
        standalone comment line directly above it."""
        m = _GUARDED_BY_RE.search(self._comment_for(line))
        return normalize_expr(m.group(1)) if m else None


@dataclass
class Project:
    root: Path
    files: list[SourceFile]

    def package_files(self) -> list[SourceFile]:
        """Files outside tests/ and tools/ (the shipped package + scripts)."""
        return [f for f in self.files
                if not f.rel.startswith(("tests/", "tools/"))]

    def test_files(self) -> list[SourceFile]:
        return [f for f in self.files if f.rel.startswith("tests/")]


def normalize_expr(src: str) -> str:
    return src.replace(" ", "")


def expr_text(node: ast.AST) -> str:
    try:
        return normalize_expr(ast.unparse(node))
    except Exception:
        return "<unparseable>"


def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _comment_map(text: str) -> dict[int, str]:
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass  # the AST parse decides whether the file is usable at all
    return out


def load_file(root: Path, path: Path) -> SourceFile | None:
    try:
        text = path.read_text(encoding="utf-8")
        tree = ast.parse(text, filename=str(path))
    except (OSError, SyntaxError, ValueError):
        return None
    return SourceFile(
        path=path, rel=path.relative_to(root).as_posix(), text=text,
        tree=tree, comments=_comment_map(text), lines=text.splitlines(),
    )


_SKIP_DIRS = {".git", "__pycache__", ".venv", "venv", "node_modules",
              ".claude", "build", "dist"}


def load_project(root: str | Path) -> Project:
    root = Path(root).resolve()
    files: list[SourceFile] = []
    for path in sorted(root.rglob("*.py")):
        if any(part in _SKIP_DIRS or part.endswith(".egg-info")
               for part in path.relative_to(root).parts[:-1]):
            continue
        sf = load_file(root, path)
        if sf is not None:
            files.append(sf)
    return Project(root=root, files=files)


# -- baseline ------------------------------------------------------------
#
# The baseline is a MULTISET: identical-message findings (e.g. two
# unguarded accesses to the same field in one file) are tracked by count
# via an ``[xN]`` suffix, so baselining one occurrence never silently
# accepts a second one added later.

_BASELINE_COUNT_RE = re.compile(r"^(.*?)\s*\[x(\d+)\]$")


def read_baseline(root: Path, name: str = BASELINE_NAME) -> dict[str, int]:
    """Normalized entry -> accepted occurrence count.  ``name`` lets sibling
    checkers (tools.graftcheck) share the format with their own file."""
    path = root / name
    out: dict[str, int] = {}
    if not path.exists():
        return out
    for line in path.read_text(encoding="utf-8").splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _BASELINE_COUNT_RE.match(line)
        if m:
            out[m.group(1)] = out.get(m.group(1), 0) + int(m.group(2))
        else:
            out[line] = out.get(line, 0) + 1
    return out


def write_baseline(root: Path, findings: list[Finding],
                   name: str = BASELINE_NAME, tool: str = "graftlint") -> Path:
    path = root / name
    lines = [
        f"# {tool} accepted debt.  One normalized finding per line",
        "# (path: RULE message — no line numbers, so edits moving code",
        "# around never resurrect an entry; repeated identical findings",
        "# carry an [xN] count).  Regenerate deliberately with:",
        f"#   python -m tools.{tool} --baseline-write",
        "# Prefer fixing or suppressing-with-reason at the site over",
        "# baselining; every entry here should be a conscious debt note.",
    ]
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.normalized()] = counts.get(f.normalized(), 0) + 1
    lines += [key if n == 1 else f"{key} [x{n}]"
              for key, n in sorted(counts.items())]
    path.write_text("\n".join(lines) + "\n", encoding="utf-8")
    return path


def stale_entries(findings: list[Finding],
                  baseline: dict[str, int]) -> list[str]:
    """Baseline entries whose accepted count exceeds what still occurs —
    fixed debt that should be pruned with --baseline-write.  Shared by both
    checkers' CLIs and the tools.check front door (which escalates these
    to errors)."""
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.normalized()] = counts.get(f.normalized(), 0) + 1
    return sorted(
        key for key, n in baseline.items() if n > counts.get(key, 0)
    )


def split_new(findings: list[Finding], baseline: dict[str, int]
              ) -> tuple[list[Finding], list[Finding]]:
    """(new, accepted) relative to the baseline.  Each baseline entry
    absorbs at most its accepted count of matching findings."""
    remaining = dict(baseline)
    new: list[Finding] = []
    old: list[Finding] = []
    for f in findings:
        key = f.normalized()
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
