"""GL501 — test hygiene: no wall-clock ``time.sleep`` in fast tests.

A ``time.sleep`` in a non-``slow`` test is either a hidden race (the test
passes because 50 ms usually suffices — until CI is loaded) or wasted
wall-clock multiplied by every tier-1 run.  The deterministic levers this
tree already owns — the fault plane's ``stall``/``delay`` actions, the
injectable ``StepTimer`` clock — replace both shapes.

Flagged: any ``time.sleep(...)`` (or bare ``sleep`` imported from
``time``) under ``tests/`` whose enclosing function, class, or module is
not marked ``pytest.mark.slow``.  ``time.sleep(0)`` (a bare GIL yield) is
allowed; ``asyncio.sleep`` is not wall-clock blocking and is out of
scope.  Suppress a justified wait with ``# graftlint: ignore[GL501](why)``.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, SourceFile

RULE = "GL501"


def _is_slow_marker(node: ast.AST) -> bool:
    text = ast.unparse(node) if hasattr(ast, "unparse") else ""
    return "mark.slow" in text or text.endswith("slow")


def _module_is_slow(sf: SourceFile) -> bool:
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "pytestmark"
                        for t in node.targets)):
            if _is_slow_marker(node.value):
                return True
    return False


def _sleep_from_time(sf: SourceFile) -> bool:
    """Whether bare ``sleep`` in this module is ``time.sleep``."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            if any(a.name == "sleep" for a in node.names):
                return True
    return False


def _walk(sf: SourceFile, node: ast.AST, slow: bool, bare_sleep: bool,
          findings: list[Finding]) -> None:
    """Uniform descent accumulating ``slow`` at every def/class boundary,
    so a slow-marked test nested under a module-level compound statement
    (``if sys.platform ...:``) keeps its exemption."""
    if isinstance(node, (ast.ClassDef, ast.FunctionDef,
                         ast.AsyncFunctionDef)):
        slow = slow or any(_is_slow_marker(d) for d in node.decorator_list)
    if isinstance(node, ast.Call) and not slow:
        _maybe_flag(sf, node, bare_sleep, findings)
    for child in ast.iter_child_nodes(node):
        _walk(sf, child, slow, bare_sleep, findings)


def _maybe_flag(sf: SourceFile, node: ast.Call, bare_sleep: bool,
                findings: list[Finding]) -> None:
    f = node.func
    is_sleep = (
        (isinstance(f, ast.Attribute) and f.attr == "sleep"
         and isinstance(f.value, ast.Name) and f.value.id == "time")
        or (bare_sleep and isinstance(f, ast.Name) and f.id == "sleep")
    )
    if not is_sleep:
        return
    if (node.args and isinstance(node.args[0], ast.Constant)
            and not node.args[0].value):
        return  # time.sleep(0): a GIL yield, not a wait
    if sf.suppressed(RULE, node.lineno):
        return
    findings.append(Finding(
        RULE, sf.rel, node.lineno,
        "wall-clock time.sleep in a non-slow test — use the fault "
        "plane (stall/delay), an injected clock, or mark the test "
        "slow",
    ))


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.test_files():
        _walk(sf, sf.tree, _module_is_slow(sf), _sleep_from_time(sf),
              findings)
    return findings
