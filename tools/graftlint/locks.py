"""GL1xx — lock-discipline race detector.

Shared instance fields are declared with a trailing (or directly
preceding) comment on their ``__init__`` assignment::

    self.queue: deque[_Request] = deque()  # guarded-by: self._lock

and every OTHER read or write of ``self.queue`` inside the declaring class
must then sit lexically inside ``with self._lock:`` — the bug shape this
catches is exactly PR 3's GIL-reliant queue/row scans: code that happened
to be atomic under CPython's GIL and nothing else.

Two lock spellings are understood:

- a real lock expression (``self._lock``, ``self._submit_lock``): guarded
  means an enclosing ``with <that expression>:`` block;
- the special name ``event-loop``: the field is confined to the asyncio
  event loop — guarded means the INNERMOST enclosing function is an
  ``async def`` (single-threaded by construction; a sync def nested in a
  coroutine runs wherever it is called and needs ``holds(event-loop)``).

Escapes, both requiring a non-empty reason:

- ``# graftlint: unguarded-ok(<reason>)`` on the access line;
- ``# graftlint: holds(<lock>)`` on a ``def`` — the caller holds the lock
  for the whole function (lock-split helpers, loop-confined sync helpers).

``__init__`` is exempt (the object is not yet shared while it runs).

GL102: modules that are REQUIRED to carry annotations (the threaded core:
batcher, server, observability, coordinator) but declare none — so
deleting the annotations can never silently disable the rule.

Known limitation (documented in README): only ``self.<field>`` accesses
inside the declaring class are checked.  Cross-object accesses
(``other.batcher.queue``) are out of AST reach — route them through a
locked accessor on the owning class.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, SourceFile, expr_text

RULE_ACCESS = "GL101"
RULE_MISSING = "GL102"

EVENT_LOOP = "event-loop"

# Modules that must declare at least one guarded-by annotation: the
# threaded serving core whose cross-thread contracts this rule exists for.
REQUIRED_MODULES = (
    "distributed_llms_tpu/runtime/batcher.py",
    "distributed_llms_tpu/runtime/server.py",
    "distributed_llms_tpu/core/observability.py",
    "distributed_llms_tpu/cluster/coordinator.py",
)


def _annotated_fields(sf: SourceFile, cls: ast.ClassDef) -> dict[str, str]:
    """{field name: lock expr} for ``self.X = ...`` statements carrying a
    ``# guarded-by:`` comment anywhere in the class body."""
    out: dict[str, str] = {}
    for node in ast.walk(cls):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name) and t.value.id == "self"):
                lock = sf.guarded_by(node.lineno)
                if lock is not None:
                    out[t.attr] = lock
    return out


class _AccessChecker(ast.NodeVisitor):
    """Walk one class, tracking the lexical ``with`` stack and the
    enclosing function, flagging unguarded annotated-field accesses."""

    def __init__(self, sf: SourceFile, cls: ast.ClassDef,
                 fields: dict[str, str]) -> None:
        self.sf = sf
        self.cls = cls
        self.fields = fields
        self.findings: list[Finding] = []
        self._with_stack: list[str] = []
        self._fn_stack: list[ast.FunctionDef | ast.AsyncFunctionDef] = []

    # -- scope tracking --------------------------------------------------

    def _visit_fn(self, node) -> None:
        self._fn_stack.append(node)
        outer_with = self._with_stack
        # ``with`` blocks do not cross function boundaries: a closure
        # defined inside a locked region runs whenever it is CALLED, not
        # where it is defined — but holds() annotations do apply.
        self._with_stack = sorted(self.sf.holds_locks(node))
        self.generic_visit(node)
        self._with_stack = outer_with
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _visit_with(self, node) -> None:
        held = [expr_text(item.context_expr) for item in node.items]
        self._with_stack.extend(held)
        self.generic_visit(node)
        del self._with_stack[len(self._with_stack) - len(held):]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    # -- the check -------------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)
        if not (isinstance(node.value, ast.Name) and node.value.id == "self"):
            return
        lock = self.fields.get(node.attr)
        if lock is None or not self._fn_stack:
            return
        fn = self._fn_stack[-1]
        if fn.name == "__init__" and len(self._fn_stack) == 1:
            # Construction: the object is not shared yet.  Deliberately
            # only __init__'s direct body — a closure DEFINED there may be
            # called much later, from any thread.
            return
        if self._guarded(fn, lock):
            return
        if self.sf.suppressed(RULE_ACCESS, node.lineno, lock_alias=True):
            return
        what = ("outside an async def (event-loop-confined field)"
                if lock == EVENT_LOOP else f"outside 'with {lock}:'")
        self.findings.append(Finding(
            RULE_ACCESS, self.sf.rel, node.lineno,
            f"unguarded access to '{self.cls.name}.{node.attr}' "
            f"(guarded-by: {lock}) {what}",
        ))

    def _guarded(self, fn, lock: str) -> bool:
        if lock in self.sf.holds_locks(fn):
            return True
        if lock == EVENT_LOOP:
            # Confinement, not a lock: a coroutine BODY runs on the loop,
            # but a sync def nested inside one runs wherever it is CALLED
            # (run_in_executor, a thread) — only the innermost function
            # counts; off-loop helpers need holds(event-loop).
            return isinstance(self._fn_stack[-1], ast.AsyncFunctionDef)
        return lock in self._with_stack


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.package_files():
        annotated_any = False
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            fields = _annotated_fields(sf, node)
            if not fields:
                continue
            annotated_any = True
            checker = _AccessChecker(sf, node, fields)
            # Visit methods only (class-body statements run once, at
            # definition time, before any instance exists).
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    checker.visit(stmt)
            findings.extend(checker.findings)
        if sf.rel in REQUIRED_MODULES and not annotated_any:
            findings.append(Finding(
                RULE_MISSING, sf.rel, 1,
                "threaded module declares no '# guarded-by:' annotations "
                "(the lock-discipline rule has nothing to check here — "
                "annotate the shared fields)",
            ))
    return findings
