"""GL3xx — registry drift.

Three string-keyed namespaces in this codebase historically grew by
convention: fault-injection site names, metric names, and ``dlt-serve``
CLI flags.  A typo in any of them is a silent no-op (a fault rule that
never fires, a dashboard counter that never moves, a flag that falls
through to a default).  These rules pin each namespace to a single
declared registry:

- GL301: every ``FaultPlane`` site string used anywhere (``.fire(...)``
  calls, ``_apply_frame_fault`` calls, ``FaultPlane.parse``/``.add``
  literals — in tests too, for dotted site names) must appear in
  ``FAULT_SITES`` in ``runtime/faults.py``.
- GL302: every metric name passed to ``METRICS.inc / set_gauge /
  set_gauges / observe / timer`` in the package must appear in
  ``METRIC_DOCS`` in ``core/observability.py``.  f-string names are
  checked as patterns (each interpolation becomes ``*``) and must be
  registered VERBATIM as that pattern (e.g. ``faults.fired.*``); a fully
  dynamic name needs an ``ignore[GL302](<reason>)``.
- GL303: every ``dlt-serve`` flag (``cli/serve_main.py``) must be declared
  either in ``_RUNTIME_FLAGS`` (flag -> RuntimeConfig field, field
  existence checked) or ``_SERVER_ONLY_FLAGS`` (server plumbing with no
  config twin) — and in exactly one of them.
- GL304: the README tables rendered from FAULT_SITES / METRIC_DOCS
  (between ``<!-- graftlint:...-sites:begin/end -->`` markers) must match
  the registries byte-for-byte (``--write-docs`` regenerates them).
- GL305: the reverse drift — a registry/declaration entry nothing uses.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from pathlib import Path

from .core import Finding, Project, SourceFile, dotted_name

RULE_FAULT = "GL301"
RULE_METRIC = "GL302"
RULE_FLAG = "GL303"
RULE_DOCS = "GL304"
RULE_UNUSED = "GL305"

FAULTS_MODULE = "runtime/faults.py"
OBS_MODULE = "core/observability.py"
SERVE_MODULE = "cli/serve_main.py"
CONFIG_MODULE = "core/config.py"

_METRIC_METHODS = {"inc", "set_gauge", "observe", "timer"}


def _find_module(project: Project, suffix: str) -> SourceFile | None:
    return next((f for f in project.package_files()
                 if f.rel.endswith(suffix)), None)


def _literal_dict(sf: SourceFile, name: str) -> dict[str, str] | None:
    """A module-level ``NAME = {str: str}`` dict literal, else None.
    Also imported by tools/graftflow (LOCK_ORDER / FAULT_SITES reads) —
    the registry idiom must parse identically across the tools."""
    for node in sf.tree.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                value = node.value
                if isinstance(value, ast.Dict):
                    out: dict[str, str] = {}
                    for k, v in zip(value.keys, value.values):
                        if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                                and isinstance(v, ast.Constant)
                                and isinstance(v.value, str)):
                            out[k.value] = v.value
                    return out
    return None


def _literal_strset(sf: SourceFile, name: str) -> set[str] | None:
    """A module-level ``NAME = frozenset({...})`` / set / tuple of str.
    Also imported by tools/graftflow (MESSAGE_TYPES reads)."""
    for node in sf.tree.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                consts = [
                    n.value for n in ast.walk(node.value)
                    if isinstance(n, ast.Constant) and isinstance(n.value, str)
                ]
                return set(consts)
    return None


# -- GL301: fault sites ---------------------------------------------------

def _sites_in_spec(spec: str) -> list[str]:
    """Site names out of a fault-spec literal (grammar:
    ``site[/tag]:action[@when][:arg]``, comma-separated)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        out.append(part.split(":", 1)[0].partition("/")[0])
    return out


def _fault_site_uses(sf: SourceFile, tests: bool) -> list[tuple[str, int]]:
    """(site, line) pairs used in ``sf``.  In test files only dotted site
    names count — the fault-grammar unit tests use synthetic one-letter
    sites on purpose."""
    uses: list[tuple[str, int]] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
            continue
        recv_text = (dotted_name(node.func) or "").lower()
        attr = node.func.attr if isinstance(node.func, ast.Attribute) else None
        fn_name = node.func.id if isinstance(node.func, ast.Name) else None
        if attr == "fire" or fn_name == "_apply_frame_fault":
            uses.append((first.value, node.lineno))
        elif (attr in ("add", "parse")
                and ("fault" in recv_text or "plane" in recv_text)):
            if attr == "add":
                uses.append((first.value, node.lineno))
            else:
                uses.extend((s, node.lineno)
                            for s in _sites_in_spec(first.value))
    if tests:
        uses = [(s, ln) for s, ln in uses if "." in s]
    return uses


def check_fault_sites(project: Project) -> list[Finding]:
    reg_file = _find_module(project, FAULTS_MODULE)
    if reg_file is None:
        return []
    registry = _literal_dict(reg_file, "FAULT_SITES")
    if registry is None:
        return [Finding(RULE_FAULT, reg_file.rel, 1,
                        "no FAULT_SITES registry (dict[str, str] of "
                        "site -> one-line doc) declared")]
    findings: list[Finding] = []
    used: set[str] = set()
    for sf in project.files:
        if sf.rel.startswith("tools/"):
            continue
        for site, line in _fault_site_uses(sf, tests=sf.rel.startswith("tests/")):
            used.add(site)
            if site not in registry and not sf.suppressed(RULE_FAULT, line):
                findings.append(Finding(
                    RULE_FAULT, sf.rel, line,
                    f"fault site '{site}' is not declared in FAULT_SITES "
                    f"({reg_file.rel}) — a typo here is a rule that never "
                    f"fires",
                ))
    for site in sorted(set(registry) - used):
        findings.append(Finding(
            RULE_UNUSED, reg_file.rel, 1,
            f"FAULT_SITES entry '{site}' is fired nowhere in the tree",
        ))
    return findings


# -- GL302: metric names --------------------------------------------------

def _pattern_of(node: ast.expr) -> str | None:
    """A checkable name for a metric-name expression: the literal itself,
    or an f-string collapsed to a ``*`` pattern.  None = fully dynamic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                if not parts or parts[-1] != "*":
                    parts.append("*")
        return "".join(parts)
    return None


def _metric_name_nodes(sf: SourceFile) -> list[tuple[ast.expr, int]]:
    out: list[tuple[ast.expr, int]] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "METRICS"):
            continue
        if f.attr in _METRIC_METHODS and node.args:
            out.append((node.args[0], node.lineno))
        elif f.attr == "set_gauges" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Dict):
                out.extend((k, k.lineno) for k in arg.keys if k is not None)
            elif isinstance(arg, ast.DictComp):
                out.append((arg.key, arg.key.lineno))
            else:
                out.append((arg, node.lineno))
    return out


def _registered(name: str, registry: dict[str, str]) -> bool:
    if name in registry:  # literal entry, or a pattern registered verbatim
        return True
    if "*" not in name:
        return any("*" in key and fnmatch.fnmatchcase(name, key)
                   for key in registry)
    return False


def check_metrics(project: Project) -> list[Finding]:
    reg_file = _find_module(project, OBS_MODULE)
    if reg_file is None:
        return []
    registry = _literal_dict(reg_file, "METRIC_DOCS")
    if registry is None:
        return [Finding(RULE_METRIC, reg_file.rel, 1,
                        "no METRIC_DOCS registry (dict[str, str] of metric "
                        "name/pattern -> one-line doc) declared")]
    findings: list[Finding] = []
    used: set[str] = set()
    for sf in project.package_files():
        for name_node, line in _metric_name_nodes(sf):
            pattern = _pattern_of(name_node)
            if pattern is not None:
                # Count the use BEFORE the suppression check: a registered
                # name emitted only at a suppressed site must not draw a
                # false GL305 "emitted nowhere".
                used.add(pattern)
            if sf.suppressed(RULE_METRIC, line):
                continue
            if pattern is None:
                findings.append(Finding(
                    RULE_METRIC, sf.rel, line,
                    "metric name is a runtime-computed expression — "
                    "graftlint cannot check it against METRIC_DOCS; use a "
                    "literal/f-string or ignore[GL302](why)",
                ))
                continue
            if not _registered(pattern, registry):
                findings.append(Finding(
                    RULE_METRIC, sf.rel, line,
                    f"metric '{pattern}' is not declared in METRIC_DOCS "
                    f"({reg_file.rel}) — dashboards can't find what the "
                    f"registry doesn't name",
                ))
    for key in sorted(registry):
        hit = key in used or (
            "*" in key and any(fnmatch.fnmatchcase(u, key)
                               for u in used if "*" not in u))
        if not hit:
            findings.append(Finding(
                RULE_UNUSED, reg_file.rel, 1,
                f"METRIC_DOCS entry '{key}' is emitted nowhere in the "
                f"package",
            ))
    return findings


# -- GL303: dlt-serve flags ----------------------------------------------

def _runtime_fields(project: Project) -> set[str] | None:
    cfg = _find_module(project, CONFIG_MODULE)
    if cfg is None:
        return None
    for node in ast.walk(cfg.tree):
        if isinstance(node, ast.ClassDef) and node.name == "RuntimeConfig":
            return {
                stmt.target.id for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            }
    return None


def check_cli_flags(project: Project) -> list[Finding]:
    serve = _find_module(project, SERVE_MODULE)
    if serve is None:
        return []
    fields = _runtime_fields(project) or set()
    runtime_flags = _literal_dict(serve, "_RUNTIME_FLAGS")
    server_only = _literal_strset(serve, "_SERVER_ONLY_FLAGS")
    if runtime_flags is None or server_only is None:
        return [Finding(RULE_FLAG, serve.rel, 1,
                        "dlt-serve must declare _RUNTIME_FLAGS (flag -> "
                        "RuntimeConfig field) and _SERVER_ONLY_FLAGS")]
    findings: list[Finding] = []
    flags: list[tuple[str, int]] = []
    for node in ast.walk(serve.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"):
            # The long name may not be the first positional (short aliases
            # like add_argument("-p", "--port", ...) come before it).
            for arg in node.args:
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and arg.value.startswith("--")):
                    flags.append((arg.value[2:], node.lineno))
                    break
    seen = set()
    for flag, line in flags:
        seen.add(flag)
        in_rt, in_srv = flag in runtime_flags, flag in server_only
        if in_rt and in_srv:
            findings.append(Finding(
                RULE_FLAG, serve.rel, line,
                f"--{flag} is declared BOTH runtime-backed and "
                f"server-only; pick one",
            ))
        elif not in_rt and not in_srv:
            findings.append(Finding(
                RULE_FLAG, serve.rel, line,
                f"--{flag} is declared in neither _RUNTIME_FLAGS nor "
                f"_SERVER_ONLY_FLAGS — say whether it shadows a "
                f"RuntimeConfig field",
            ))
        elif in_rt and runtime_flags[flag] not in fields:
            findings.append(Finding(
                RULE_FLAG, serve.rel, line,
                f"--{flag} maps to RuntimeConfig.{runtime_flags[flag]}, "
                f"which does not exist",
            ))
    for flag in sorted((set(runtime_flags) | server_only) - seen):
        findings.append(Finding(
            RULE_UNUSED, serve.rel, 1,
            f"declared dlt-serve flag '--{flag}' has no add_argument",
        ))
    return findings


# -- GL304: README tables -------------------------------------------------

def render_fault_table(registry: dict[str, str]) -> str:
    lines = ["| site | fires at |", "| --- | --- |"]
    lines += [f"| `{site}` | {doc} |" for site, doc in sorted(registry.items())]
    return "\n".join(lines)


def render_metric_table(registry: dict[str, str]) -> str:
    lines = ["| metric | meaning |", "| --- | --- |"]
    lines += [f"| `{name}` | {doc} |" for name, doc in sorted(registry.items())]
    return "\n".join(lines)


_MARKERS = {
    "fault-sites": render_fault_table,
    "metrics": render_metric_table,
}


def _marker_re(tag: str) -> re.Pattern[str]:
    return re.compile(
        rf"<!-- graftlint:{tag}:begin -->\n(.*?)<!-- graftlint:{tag}:end -->",
        re.S,
    )


def _registries(project: Project) -> dict[str, dict[str, str]]:
    out = {}
    faults = _find_module(project, FAULTS_MODULE)
    obs = _find_module(project, OBS_MODULE)
    out["fault-sites"] = (_literal_dict(faults, "FAULT_SITES") or {}) \
        if faults else {}
    out["metrics"] = (_literal_dict(obs, "METRIC_DOCS") or {}) if obs else {}
    return out


def check_docs(project: Project) -> list[Finding]:
    readme = project.root / "README.md"
    if not readme.exists():
        return []
    text = readme.read_text(encoding="utf-8")
    regs = _registries(project)
    findings: list[Finding] = []
    for tag, renderer in _MARKERS.items():
        m = _marker_re(tag).search(text)
        if m is None:
            findings.append(Finding(
                RULE_DOCS, "README.md", 1,
                f"missing '<!-- graftlint:{tag}:begin/end -->' block — run "
                f"python -m tools.graftlint --write-docs",
            ))
            continue
        want = renderer(regs[tag])
        if m.group(1).strip() != want.strip():
            line = text[: m.start()].count("\n") + 1
            findings.append(Finding(
                RULE_DOCS, "README.md", line,
                f"'{tag}' table is stale vs the code registry — run "
                f"python -m tools.graftlint --write-docs",
            ))
    return findings


def write_docs(project: Project) -> list[str]:
    """Regenerate the README registry tables in place.  Returns the tags
    rewritten (missing README or marker blocks are skipped, not
    invented)."""
    readme = project.root / "README.md"
    if not readme.exists():
        return []
    text = readme.read_text(encoding="utf-8")
    regs = _registries(project)
    done: list[str] = []
    for tag, renderer in _MARKERS.items():
        pat = _marker_re(tag)
        if pat.search(text) is None:
            continue
        block = (f"<!-- graftlint:{tag}:begin -->\n{renderer(regs[tag])}\n"
                 f"<!-- graftlint:{tag}:end -->")
        # Callable replacement: a backslash in a registry doc string must
        # not be interpreted as a re.sub escape sequence.
        text = pat.sub(lambda _m, _b=block: _b, text)
        done.append(tag)
    readme.write_text(text, encoding="utf-8")
    return done


def check(project: Project) -> list[Finding]:
    return (check_fault_sites(project) + check_metrics(project)
            + check_cli_flags(project) + check_docs(project))
