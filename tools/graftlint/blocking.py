"""GL401 — blocking calls in the engine-loop call graph.

``ContinuousBatcher.run`` is the latency floor of serving: every request's
tokens pass through it, and ONE blocking syscall in its call graph stalls
every in-flight row (the engine thread owns the device — nothing else can
dispatch while it waits).  This rule builds the intra-repo call graph from
``ContinuousBatcher.run`` (same-module functions, ``self.*`` methods, and
the known collaborator fields ``self.pool`` -> PagePool,
``self.prefix_cache`` -> PrefixCache, ``self.faults`` -> FaultPlane —
one-step local aliases like ``pc = self.prefix_cache`` included) and flags
any reachable call to:

- ``time.sleep``
- socket construction / connection (``socket.socket``, ``create_connection``)
- ``subprocess.*`` / ``os.system`` / ``os.popen``
- file I/O: builtin ``open``, ``Path.read_text/write_text/read_bytes/
  write_bytes``
- ``requests.*`` / ``urllib.request.*``

A deliberate block (the fault plane's ``stall`` action models a wedged
device call) carries ``# graftlint: ignore[GL401](<reason>)``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .core import Finding, Project, SourceFile, dotted_name

RULE = "GL401"

ENTRY_CLASS = "ContinuousBatcher"
ENTRY_METHOD = "run"

# self.<field> -> class whose methods the call resolves to.
_FIELD_CLASSES = {
    "pool": "PagePool",
    "prefix_cache": "PrefixCache",
    "faults": "FaultPlane",
}

_BLOCKING_DOTTED = (
    "time.sleep", "socket.socket", "socket.create_connection",
    "os.system", "os.popen",
)
_BLOCKING_PREFIXES = ("subprocess.", "requests.", "urllib.request.")
_BLOCKING_METHODS = {"read_text", "write_text", "read_bytes", "write_bytes"}


@dataclass(frozen=True)
class _FnKey:
    rel: str
    cls: str | None  # None = module-level function
    name: str


def _collect_defs(files: list[SourceFile]) -> dict[_FnKey, tuple[SourceFile, ast.AST]]:
    defs: dict[_FnKey, tuple[SourceFile, ast.AST]] = {}
    for sf in files:
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[_FnKey(sf.rel, None, node.name)] = (sf, node)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        defs[_FnKey(sf.rel, node.name, sub.name)] = (sf, sub)
    return defs


def _local_aliases(fn: ast.AST) -> dict[str, str]:
    """{local name: collaborator class} for ``x = self.<known field>``."""
    out: dict[str, str] = {}
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Attribute)
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "self"
                and node.value.attr in _FIELD_CLASSES):
            out[node.targets[0].id] = _FIELD_CLASSES[node.value.attr]
    return out


def _callees(sf: SourceFile, key: _FnKey, fn: ast.AST,
             defs: dict[_FnKey, tuple[SourceFile, ast.AST]]) -> set[_FnKey]:
    aliases = _local_aliases(fn)
    out: set[_FnKey] = set()

    def resolve(cls: str | None, name: str) -> None:
        for cand in defs:
            if cand.name == name and cand.cls == cls:
                out.add(cand)

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            resolve(None, f.id)
            # Same-class unbound-style calls are not used in this tree.
        elif isinstance(f, ast.Attribute):
            v = f.value
            if isinstance(v, ast.Name) and v.id == "self":
                resolve(key.cls, f.attr)
            elif (isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name) and v.value.id == "self"
                    and v.attr in _FIELD_CLASSES):
                resolve(_FIELD_CLASSES[v.attr], f.attr)
            elif isinstance(v, ast.Name) and v.id in aliases:
                resolve(aliases[v.id], f.attr)
    return out


def _blocking_calls(sf: SourceFile, fn: ast.AST) -> list[tuple[int, str]]:
    out: list[tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name in _BLOCKING_DOTTED or (
                name is not None and name.startswith(_BLOCKING_PREFIXES)):
            out.append((node.lineno, name))
        elif isinstance(node.func, ast.Name) and node.func.id == "open":
            out.append((node.lineno, "open"))
        elif (isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_METHODS):
            out.append((node.lineno, f"<..>.{node.func.attr}"))
    return out


def check(project: Project) -> list[Finding]:
    # The graph spans the batcher module and the fault plane it consults.
    scope = [sf for sf in project.package_files()
             if sf.rel.endswith(("runtime/batcher.py", "runtime/faults.py"))
             or sf.rel in ("batcher.py", "faults.py")]
    defs = _collect_defs(scope)
    entry = next((k for k in defs
                  if k.cls == ENTRY_CLASS and k.name == ENTRY_METHOD), None)
    if entry is None:
        return []
    # BFS over the call graph.
    reachable: list[_FnKey] = [entry]
    seen = {entry}
    i = 0
    while i < len(reachable):
        key = reachable[i]
        i += 1
        sf, fn = defs[key]
        for callee in _callees(sf, key, fn, defs):
            if callee not in seen:
                seen.add(callee)
                reachable.append(callee)
    findings: list[Finding] = []
    for key in reachable:
        sf, fn = defs[key]
        where = f"{key.cls}.{key.name}" if key.cls else key.name
        for line, what in _blocking_calls(sf, fn):
            if sf.suppressed(RULE, line):
                continue
            findings.append(Finding(
                RULE, sf.rel, line,
                f"blocking call '{what}' in {where}, reachable from "
                f"{ENTRY_CLASS}.{ENTRY_METHOD} — the engine loop thread "
                f"must never block off-device",
            ))
    return findings
