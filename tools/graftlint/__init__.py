"""graftlint — repo-native static analysis for distributed_llms_tpu.

Five rule families, each born from a bug class this tree actually shipped
and had to retrofit-fix:

- GL1xx lock discipline (``locks``): ``# guarded-by:`` annotated shared
  fields must be accessed under their lock / event-loop confinement.
- GL2xx JAX hot-path hygiene (``hotpath``): no implicit host syncs or
  Python control flow on traced values in ``ops/``, ``models/``,
  ``runtime/sampling.py``.
- GL3xx registry drift (``registry``): fault sites vs FAULT_SITES, metric
  names vs METRIC_DOCS, dlt-serve flags vs RuntimeConfig, README tables
  vs both registries.
- GL401 blocking calls (``blocking``): nothing reachable from
  ``ContinuousBatcher.run`` may sleep or touch sockets/files.
- GL501 test hygiene (``testhygiene``): no wall-clock sleeps in fast
  tests.

Run as ``python -m tools.graftlint`` (exit 0 = no non-baselined findings)
or through the tier-1 gate ``tests/tools/test_graftlint.py``.
"""

from __future__ import annotations

from . import blocking, hotpath, locks, registry, testhygiene
from .core import (BASELINE_NAME, Finding, Project, load_project,
                   read_baseline, split_new, write_baseline)

RULE_MODULES = (locks, hotpath, registry, blocking, testhygiene)


def run_project(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for mod in RULE_MODULES:
        findings.extend(mod.check(project))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.message))


def run(root) -> list[Finding]:
    return run_project(load_project(root))


__all__ = [
    "BASELINE_NAME", "Finding", "Project", "RULE_MODULES", "load_project",
    "read_baseline", "run", "run_project", "split_new", "write_baseline",
]
