"""GL2xx — JAX hot-path hygiene.

Scope: the modules whose code runs under ``jax.jit`` / ``shard_map`` —
``ops/``, ``models/``, and ``runtime/sampling.py``.  Everything in these
files is hot-path by policy (their functions are traced from jitted
callers even when the ``@jax.jit`` decorator lives elsewhere, e.g.
``models.model.forward`` traced by the batcher's admission programs), so
the rules apply to every function body in scope.

The failure mode is the silent host sync: an op that forces the traced
value back to Python blocks dispatch, serializes the pipeline, and on a
real TPU turns a microsecond step into a millisecond one — the exact bug
class vLLM-style stacks lint for in CI.  Four shapes:

- GL201 ``.item()`` — always a device->host sync.
- GL202 ``float()/int()/bool()`` applied to an array-producing expression
  (one containing a ``jnp.``/``lax.``/``jax.nn``-style call or an
  ``.any()/.all()/.sum()``-style reduction).  Plain ``int(cfg.heads *
  pct)`` on static config math is fine and not flagged.
- GL203 ``np.asarray/np.array/np.frombuffer`` on such an expression —
  numpy materializes, so a traced operand means a sync (static shape
  math via ``np.zeros(x.shape, ...)`` stays legal).
- GL204 Python ``if``/``while`` on such an expression — control flow on a
  traced value either fails to trace or (under ``jit``-exempt paths)
  syncs per step; use ``lax.cond``/``jnp.where``.

Suppress a deliberate sync with ``# graftlint: ignore[GL20x](<reason>)``.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, SourceFile, dotted_name

RULE_ITEM = "GL201"
RULE_CAST = "GL202"
RULE_NUMPY = "GL203"
RULE_BRANCH = "GL204"

# Call roots that produce (or operate on) traced arrays.  Bare ``jax.`` is
# deliberately absent: ``jax.default_backend()``, ``jax.devices()`` and
# friends are host-side introspection.
_ARRAY_ROOTS = ("jnp.", "lax.", "jax.numpy.", "jax.lax.", "jax.nn.",
                "jax.random.", "jax.scipy.")
_ARRAY_METHODS = {"any", "all", "sum", "max", "min", "mean", "prod",
                  "argmax", "argmin", "astype", "reshape"}
_NUMPY_MATERIALIZERS = {"np.asarray", "np.array", "np.frombuffer",
                        "numpy.asarray", "numpy.array", "onp.asarray"}
# Dtype metadata, evaluated at trace (or import) time — never a traced
# array, so casting/branching on these is host-side and legal.
_METADATA_CALLS = {"jnp.finfo", "jnp.iinfo", "jnp.dtype", "jnp.issubdtype",
                   "jnp.result_type", "jax.numpy.finfo", "jax.numpy.iinfo",
                   "jax.numpy.dtype", "jax.eval_shape"}


def in_scope(rel: str) -> bool:
    parts = rel.split("/")
    return ("ops" in parts[:-1] or "models" in parts[:-1]
            or rel.endswith("runtime/sampling.py") or rel == "sampling.py")


def _is_array_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    if name in _METADATA_CALLS:
        return False
    if name is not None and name.startswith(_ARRAY_ROOTS):
        return True
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr in _ARRAY_METHODS)


def _contains_array_expr(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) and _is_array_call(n)
               for n in ast.walk(node))


def _check_tree(sf: SourceFile, tree: ast.AST) -> list[Finding]:
    out: list[Finding] = []

    def emit(rule: str, node: ast.AST, msg: str) -> None:
        if not sf.suppressed(rule, node.lineno):
            out.append(Finding(rule, sf.rel, node.lineno, msg))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "item"
                    and not node.args and not node.keywords):
                emit(RULE_ITEM, node,
                     "'.item()' forces a device->host sync in hot-path "
                     "code; keep the value on device (or sync once, "
                     "outside the step loop)")
                continue
            name = dotted_name(f)
            if (isinstance(f, ast.Name) and f.id in ("float", "int", "bool")
                    and node.args
                    and _contains_array_expr(node.args[0])):
                emit(RULE_CAST, node,
                     f"'{f.id}()' on an array expression is an implicit "
                     f"host sync; use jnp casts / keep it traced")
            elif (name in _NUMPY_MATERIALIZERS
                    and node.args and _contains_array_expr(node.args[0])):
                emit(RULE_NUMPY, node,
                     f"'{name}' on an array expression materializes on "
                     f"host (sync); stay in jnp, or hoist the transfer "
                     f"out of the hot path")
        elif isinstance(node, (ast.If, ast.While)):
            if _contains_array_expr(node.test):
                kind = "if" if isinstance(node, ast.If) else "while"
                emit(RULE_BRANCH, node,
                     f"Python '{kind}' on an array expression — traced "
                     f"values cannot drive host control flow; use "
                     f"lax.cond/lax.while_loop or jnp.where")
    return out


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.package_files():
        if not in_scope(sf.rel):
            continue
        findings.extend(_check_tree(sf, sf.tree))
    return findings
