"""graftcheck: abstract-interpretation contract checker.

graftlint's semantic sibling — instead of reading the AST it traces the
REAL code under abstract values (``jax.eval_shape`` / ``jax.make_jaxpr`` /
``.lower()`` on fake meshes, zero FLOPs) and holds it to declared
contracts:

- GC1xx shape/dtype contracts       (tools/graftcheck/shapes.py)
- GC2xx sharding-spec audit         (tools/graftcheck/sharding.py)
- GC3xx dtype-promotion lint        (tools/graftcheck/dtypes.py)
- GC4xx recompilation hazards       (tools/graftcheck/recompile.py)
- GC5xx donation audit              (tools/graftcheck/donation.py)
- GCD01 README contracts-table drift (tools/graftcheck/docs.py)

Run as ``python -m tools.graftcheck`` (exit 0 = clean) or through the
unified front door ``python -m tools.check``; the tier-1 pytest gate is
tests/tools/test_graftcheck.py::test_repo_is_clean.  Accepted debt lives
in ``graftcheck_baseline.txt`` (checked in EMPTY; graftlint's normalized
line-free multiset format).
"""

from __future__ import annotations

from pathlib import Path

from .core import (BASELINE_NAME, Finding, read_baseline, split_new,
                   write_baseline)

FAMILIES = ("GC1", "GC2", "GC3", "GC4", "GC5", "GCD")


def run_all(only: set[str] | None = None,
            root: str | Path = ".") -> list[Finding]:
    """Run every rule family (or the ``only`` subset of FAMILIES)."""
    from . import docs, donation, dtypes, recompile, shapes, sharding

    def want(fam: str) -> bool:
        return only is None or fam in only

    findings: list[Finding] = []
    if want("GC1"):
        findings += shapes.check()
    if want("GC2"):
        findings += sharding.check()
    if want("GC3"):
        findings += dtypes.check()
    if want("GC4"):
        findings += recompile.check()
    if want("GC5"):
        findings += donation.check()
    if want("GCD"):
        findings += docs.check_docs(Path(root))
    return findings


__all__ = [
    "BASELINE_NAME", "FAMILIES", "Finding", "read_baseline", "run_all",
    "split_new", "write_baseline",
]
