"""CLI: ``python -m tools.graftcheck [--root DIR] [--only GC1,GC4]``.

Exit status mirrors graftlint: 0 when every finding is absent or
baselined, 1 when NEW findings exist, 2 on usage errors.

- ``--only``: comma-separated rule families (GC1..GC5, GCD) — scoped runs
  for fast iteration; the gate and the front door run everything.
- ``--baseline-write``: accept current findings into
  ``graftcheck_baseline.txt``.
- ``--write-docs``: regenerate the README "Semantic checks" table.
- ``--all``: also print baselined findings.

Unlike graftlint (pure AST over ``--root``), graftcheck IMPORTS and traces
the package on sys.path; ``--root`` locates the baseline and README.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftcheck",
        description="semantic contract checker (see tools/graftcheck/)",
    )
    ap.add_argument("--root", default=".",
                    help="repo root (baseline + README location)")
    ap.add_argument("--only", default=None,
                    help="comma-separated families, e.g. GC2,GC4")
    ap.add_argument("--baseline-write", action="store_true",
                    help="accept current findings into the baseline")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate the README contracts table, then exit")
    ap.add_argument("--all", action="store_true",
                    help="also print baselined (accepted) findings")
    args = ap.parse_args(argv)

    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"graftcheck: --root {root} is not a directory",
              file=sys.stderr)
        return 2

    from tools.graftcheck import (FAMILIES, read_baseline, run_all,
                                  split_new, write_baseline)

    only = None
    if args.only:
        only = {s.strip() for s in args.only.split(",") if s.strip()}
        unknown = only - set(FAMILIES)
        if unknown:
            print(f"graftcheck: unknown families {sorted(unknown)}; "
                  f"have {FAMILIES}", file=sys.stderr)
            return 2

    if args.write_docs:
        from tools.graftcheck.docs import write_docs

        done = write_docs(root)
        print("graftcheck: rewrote README contracts table"
              if done else "graftcheck: no contracts marker block found")
        return 0

    findings = run_all(only=only, root=root)
    if args.baseline_write:
        path = write_baseline(root, findings)
        print(f"graftcheck: wrote {len(findings)} finding(s) to {path.name}")
        return 0

    baseline = read_baseline(root)
    new, accepted = split_new(findings, baseline)
    for f in new:
        print(f.render())
    if args.all:
        for f in accepted:
            print(f"{f.render()}  [baselined]")
    from tools.graftlint.core import stale_entries

    stale = stale_entries(findings, baseline)
    print(f"graftcheck: {len(new)} new finding(s), {len(accepted)} "
          f"baselined, {len(stale)} stale baseline "
          f"entr{'y' if len(stale) == 1 else 'ies'}", file=sys.stderr)
    for s in stale:
        print(f"  stale: {s}", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
