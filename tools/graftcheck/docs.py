"""GCD — README contracts-table generation and drift check.

Mirrors graftlint's registry-table discipline: the "Semantic checks" table
in README.md lives between ``<!-- graftcheck:contracts:begin/end -->``
markers, is generated from the contract registries (``python -m
tools.graftcheck --write-docs``), and a stale table is a finding — the
docs can never quietly diverge from what the gate actually pins.
"""

from __future__ import annotations

import re
from pathlib import Path

from .core import Finding
from .contracts import DOC_BEGIN, DOC_END, contracts_table

_MARKER_RE = re.compile(
    re.escape(DOC_BEGIN) + r"\n(.*?)" + re.escape(DOC_END), re.S
)


def check_docs(root: Path) -> list[Finding]:
    readme = Path(root) / "README.md"
    if not readme.exists():
        return []
    text = readme.read_text(encoding="utf-8")
    m = _MARKER_RE.search(text)
    if m is None:
        return [Finding(
            "GCD01", "README.md", 1,
            f"missing '{DOC_BEGIN}' block — run "
            "python -m tools.graftcheck --write-docs")]
    if m.group(1).strip() != contracts_table().strip():
        return [Finding(
            "GCD01", "README.md", text[: m.start()].count("\n") + 1,
            "contracts table is stale vs the registry — run "
            "python -m tools.graftcheck --write-docs")]
    return []


def write_docs(root: Path) -> bool:
    """Regenerate the table in place; returns whether a block was found."""
    readme = Path(root) / "README.md"
    if not readme.exists():
        return False
    text = readme.read_text(encoding="utf-8")
    if _MARKER_RE.search(text) is None:
        return False
    block = f"{DOC_BEGIN}\n{contracts_table()}\n{DOC_END}"
    readme.write_text(
        _MARKER_RE.sub(lambda _m: block, text), encoding="utf-8"
    )
    return True
