"""GC5 — buffer-donation audit over lowered jit entry points.

A KV cache that stops being donated doubles its HBM footprint (old + new
buffer live across the step) and nothing fails — serving just OOMs at half
the batch it used to hold.  Each contract lowers the real jitted function
with abstract arguments (no compile, no FLOPs) and reads the donation
flags off ``Lowered.args_info``:

- GC501: a leaf of a ``must_donate`` argument is not donated.
- GC502: a large buffer (>= ``min_bytes``) outside ``must_donate`` and
  ``may_keep`` is passed in non-donated — a persistent carry someone
  forgot to alias.
"""

from __future__ import annotations

import jax

from .core import Finding


def _leaf_bytes(info) -> int:
    try:
        import numpy as np

        return int(np.prod(info.shape)) * info.dtype.itemsize
    except Exception:
        return 0


def check(contracts=None) -> list[Finding]:
    if contracts is None:
        from .contracts import donation_contracts

        contracts = donation_contracts()
    findings: list[Finding] = []
    for contract in contracts:
        try:
            fn, named_args, kwargs = contract.build()
            lowered = fn.lower(
                *(v for _, v in named_args), **kwargs
            )
            pos_info, kw_info = lowered.args_info
        except Exception as exc:
            findings.append(Finding(
                "GC501", contract.path, 0,
                f"{contract.name}: lowering failed: "
                f"{type(exc).__name__}: {str(exc).splitlines()[0][:160]}"))
            continue
        # Static args (cfg etc.) are DROPPED from Lowered.args_info; the
        # remaining positional entries keep their relative order, so zip
        # the static-filtered names against them.
        names = [n for n, _ in named_args if n not in contract.static_args]
        if len(names) != len(pos_info):
            findings.append(Finding(
                "GC501", contract.path, 0,
                f"{contract.name}: args_info arity {len(pos_info)} != "
                f"{len(names)} non-static args — static_args declaration "
                "drifted from the function signature"))
            continue
        for name, info_tree in zip(names, pos_info):
            leaves = jax.tree.leaves(info_tree)
            donated = [bool(getattr(l, "donated", False)) for l in leaves]
            if name in contract.must_donate:
                if not leaves:
                    findings.append(Finding(
                        "GC501", contract.path, 0,
                        f"{contract.name}: {name} must donate but lowered "
                        "with no array leaves (pruned as unused?)"))
                elif not all(donated):
                    kept = sum(1 for d in donated if not d)
                    findings.append(Finding(
                        "GC501", contract.path, 0,
                        f"{contract.name}: {name} must be donated but "
                        f"{kept}/{len(donated)} leaves are not "
                        "(donate_argnames lost?)"))
            elif name not in contract.may_keep:
                for leaf, don in zip(leaves, donated):
                    if not don and _leaf_bytes(leaf) >= contract.min_bytes:
                        findings.append(Finding(
                            "GC502", contract.path, 0,
                            f"{contract.name}: large persistent buffer "
                            f"{name} ({tuple(leaf.shape)} {leaf.dtype}, "
                            f"{_leaf_bytes(leaf)} B) is not donated and "
                            "not declared may_keep"))
    return findings
