"""The contract registry: what graftcheck holds the code to.

Every rule module consumes a declarative registry built here — op
shape/dtype contracts (GC1), preset x mesh sharding audits and collective
audits (GC2), hot-function dtype contracts (GC3), recompilation scenarios
(GC4), and donation contracts (GC5).  The registries are also the source of
the README "Semantic checks" table (``python -m tools.graftcheck
--write-docs``), so the docs can never drift from what is actually gated.

Everything imports the REAL package lazily (inside builders) and traces the
real functions — no mocks: a contract that passes here is a program XLA
would accept with these shapes on hardware.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp

# Repo-relative paths findings attribute to (line 0: semantic findings are
# whole-file; the baseline format is line-free anyway).
P_FLASH = "distributed_llms_tpu/ops/flash.py"
P_RING = "distributed_llms_tpu/ops/ring.py"
P_ULYSSES = "distributed_llms_tpu/ops/ulysses.py"
P_DECODE = "distributed_llms_tpu/ops/decode_attn.py"
P_QMM = "distributed_llms_tpu/ops/quant_matmul.py"
P_MODEL = "distributed_llms_tpu/models/model.py"
P_SPECS = "distributed_llms_tpu/parallel/specs.py"
P_SAMPLING = "distributed_llms_tpu/runtime/sampling.py"
P_CONSTRAIN = "distributed_llms_tpu/runtime/constrain.py"
P_BATCHER = "distributed_llms_tpu/runtime/batcher.py"
P_ENGINE = "distributed_llms_tpu/runtime/engine.py"


def sds(shape, dtype):
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def key_sds():
    """Abstract typed PRNG key."""
    return jax.eval_shape(lambda: jax.random.key(0))


@functools.lru_cache(maxsize=None)
def preset(name: str, **overrides):
    from distributed_llms_tpu.models.presets import get_preset

    return get_preset(name, **overrides)


@functools.lru_cache(maxsize=None)
def abstract_params(cfg):
    from distributed_llms_tpu.models import model as model_lib

    return jax.eval_shape(
        lambda: model_lib.init_params(jax.random.key(0), cfg)
    )


def abstract_cache(cfg, batch: int, max_len: int):
    from distributed_llms_tpu.models import model as model_lib

    return jax.eval_shape(lambda: model_lib.init_cache(cfg, batch, max_len))


def abstract_pool(cfg, num_pages: int, page_size: int):
    from distributed_llms_tpu.runtime import batcher as batcher_lib

    return jax.eval_shape(
        lambda: batcher_lib._paged_pool(cfg, num_pages, page_size)
    )


def abstract_quant_pool(cfg, num_pages: int, page_size: int):
    """Int8 KV page pool (QuantKVCache: data int8 + f32 absmax scales)."""
    from distributed_llms_tpu.runtime import batcher as batcher_lib

    return jax.eval_shape(
        lambda: batcher_lib._paged_pool(cfg, num_pages, page_size, kv_bits=8)
    )


def fake_mesh(**axes: int):
    """AbstractMesh over the standard axis names — sharding semantics with
    zero devices (jax.eval_shape/make_jaxpr accept it everywhere a real
    mesh would go)."""
    from jax.sharding import AbstractMesh

    names = ("data", "pipe", "model", "seq", "expert")
    return AbstractMesh(tuple((n, axes.get(n, 1)) for n in names))


# ---------------------------------------------------------------------------
# GC1 — op shape/dtype contracts
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class OpCase:
    label: str
    fn: Callable        # callable over the abstract args
    args: tuple         # abstract (or small concrete) argument pytrees
    want: tuple         # ((shape, dtype-str), ...) for every output leaf


@dataclass(frozen=True)
class OpContract:
    name: str           # e.g. "ops.flash.flash_attention"
    path: str
    doc: str            # one line for the README table
    build: Callable[[], list[OpCase]]


def _flash_cases() -> list[OpCase]:
    from distributed_llms_tpu.ops import flash

    cases = []
    # (b, tq, s, h, kvh, d, window, dtype): batch 1, non-power-of-two
    # lengths, GQA/MQA head ratios, windowed band, both serving dtypes.
    for b, tq, s, h, kvh, d, win, dt in [
        (1, 1, 1, 4, 4, 64, None, jnp.float32),
        (2, 7, 7, 4, 2, 64, None, jnp.bfloat16),
        (3, 33, 33, 8, 1, 64, None, jnp.bfloat16),
        (2, 128, 128, 4, 4, 64, 16, jnp.bfloat16),
        (2, 16, 48, 4, 2, 64, None, jnp.float32),  # prefill into longer cache
    ]:
        q = sds((b, tq, h, d), dt)
        kv = sds((b, s, kvh, d), dt)
        qp = sds((b, tq), jnp.int32)
        kp = sds((b, s), jnp.int32)
        kval = sds((b, s), jnp.bool_)
        aligned = tq == s
        fn = (
            (lambda q, k, v: flash.flash_attention(q, k, v))
            if aligned else
            (lambda q, k, v, qp, kp, kval: flash.flash_attention(
                q, k, v, q_positions=qp, k_positions=kp, k_valid=kval))
        )
        args = (q, kv, kv) if aligned else (q, kv, kv, qp, kp, kval)
        if win is not None:
            fn = functools.partial(
                lambda w, q, k, v: flash.flash_attention(q, k, v, window=w),
                win,
            )
            args = (q, kv, kv)
        cases.append(OpCase(
            label=f"b{b} tq{tq} s{s} h{h}/{kvh} d{d} win{win} {jnp.dtype(dt).name}",
            fn=fn, args=args,
            want=(((b, tq, h, d), jnp.dtype(dt).name),),
        ))
    return cases


def _ring_cases() -> list[OpCase]:
    import functools as ft

    from distributed_llms_tpu.core import jaxcompat
    from distributed_llms_tpu.ops import ring
    from jax.sharding import PartitionSpec as P

    cases = []
    for seq, b, t, h, kvh, d, dt in [
        (2, 1, 16, 4, 2, 64, jnp.bfloat16),
        (4, 2, 32, 4, 4, 64, jnp.float32),
        (4, 2, 96, 8, 2, 64, jnp.bfloat16),  # non-pow2 global length
    ]:
        mesh = fake_mesh(seq=seq)
        body = ft.partial(ring.ring_attention, axis_name="seq")
        sh, ps = P(None, "seq", None, None), P(None, "seq")

        def fn(q, k, v, pos, body=body, mesh=mesh, sh=sh, ps=ps):
            return jaxcompat.shard_map(
                lambda q, k, v, p: body(q, k, v, p, p),
                mesh=mesh, in_specs=(sh, sh, sh, ps), out_specs=sh,
                axis_names={"seq"},
            )(q, k, v, pos)

        cases.append(OpCase(
            label=f"seq{seq} b{b} t{t} h{h}/{kvh} {jnp.dtype(dt).name}",
            fn=fn,
            args=(sds((b, t, h, d), dt), sds((b, t, kvh, d), dt),
                  sds((b, t, kvh, d), dt), sds((b, t), jnp.int32)),
            want=(((b, t, h, d), jnp.dtype(dt).name),),
        ))
    return cases


def _seq_decode_cases() -> list[OpCase]:
    from distributed_llms_tpu.core import jaxcompat
    from distributed_llms_tpu.ops import ring
    from jax.sharding import PartitionSpec as P

    cases = []
    for seq, b, s_loc, n_dec, h, kvh, d in [(2, 2, 32, 8, 4, 2, 64),
                                            (4, 1, 16, 4, 4, 4, 64)]:
        mesh = fake_mesh(seq=seq)
        seq_kv = P(None, "seq", None, None)

        def fn(q, ck, cv, dk, dv, ml, md, mesh=mesh, seq_kv=seq_kv):
            return jaxcompat.shard_map(
                lambda q, ck, cv, dk, dv, ml, md:
                    ring.seq_cached_decode_attention(
                        q, ck, cv, dk, dv, ml, md, axis_name="seq"),
                mesh=mesh,
                in_specs=(P(), seq_kv, seq_kv, P(), P(), P(None, "seq"), P()),
                out_specs=P(),
                axis_names={"seq"},
            )(q, ck, cv, dk, dv, ml, md)

        dt = jnp.bfloat16
        cases.append(OpCase(
            label=f"seq{seq} b{b} sloc{s_loc} dec{n_dec} h{h}/{kvh}",
            fn=fn,
            args=(sds((b, 1, h, d), dt),
                  sds((b, s_loc * seq, kvh, d), dt),
                  sds((b, s_loc * seq, kvh, d), dt),
                  sds((b, n_dec, kvh, d), dt), sds((b, n_dec, kvh, d), dt),
                  sds((b, s_loc * seq), jnp.bool_),
                  sds((b, n_dec), jnp.bool_)),
            want=(((b, 1, h, d), "bfloat16"),),
        ))
    return cases


def _ulysses_cases() -> list[OpCase]:
    import functools as ft

    from distributed_llms_tpu.core import jaxcompat
    from distributed_llms_tpu.ops import ulysses
    from jax.sharding import PartitionSpec as P

    cases = []
    for seq, b, t, h, kvh, d in [(2, 2, 16, 4, 2, 64), (4, 1, 32, 8, 4, 64)]:
        mesh = fake_mesh(seq=seq)
        sh, ps = P(None, "seq", None, None), P(None, "seq")
        body = ft.partial(ulysses.ulysses_attention, axis_name="seq")

        def fn(q, k, v, pos, body=body, mesh=mesh, sh=sh, ps=ps):
            return jaxcompat.shard_map(
                body, mesh=mesh, in_specs=(sh, sh, sh, ps), out_specs=sh,
                axis_names={"seq"},
            )(q, k, v, pos)

        cases.append(OpCase(
            label=f"seq{seq} b{b} t{t} h{h}/{kvh}",
            fn=fn,
            args=(sds((b, t, h, d), jnp.bfloat16),
                  sds((b, t, kvh, d), jnp.bfloat16),
                  sds((b, t, kvh, d), jnp.bfloat16),
                  sds((b, t), jnp.int32)),
            want=(((b, t, h, d), "bfloat16"),),
        ))
    return cases


def _ragged_cases() -> list[OpCase]:
    from distributed_llms_tpu.ops import decode_attn

    cases = []
    for b, s, h, kvh, d, win in [
        (1, 128, 4, 2, 128, None),   # kernel-tileable width
        (3, 384, 8, 2, 128, None),   # 128-multiple but not 512: block stepdown
        (2, 40, 4, 4, 64, None),     # untileable -> dense fallback path
        (2, 256, 4, 2, 128, 64),     # windowed band
    ]:
        dt = jnp.bfloat16
        cases.append(OpCase(
            label=f"b{b} s{s} h{h}/{kvh} d{d} win{win}",
            fn=functools.partial(
                lambda w, q, k, v, ln: decode_attn.ragged_decode_attention(
                    q, k, v, ln, window=w), win),
            args=(sds((b, 1, h, d), dt), sds((b, s, kvh, d), dt),
                  sds((b, s, kvh, d), dt), sds((b,), jnp.int32)),
            want=(((b, 1, h, d), "bfloat16"),),
        ))
    return cases


def _paged_cases() -> list[OpCase]:
    from distributed_llms_tpu.ops import decode_attn

    cases = []
    for b, nb, blk, p, h, kvh, d in [
        (1, 16, 8, 4, 4, 2, 128),    # page-boundary: length can hit p*blk
        (3, 8, 64, 2, 4, 4, 64),     # untileable d -> gather fallback
        (2, 32, 16, 8, 8, 2, 128),
    ]:
        dt = jnp.bfloat16
        cases.append(OpCase(
            label=f"b{b} nb{nb} blk{blk} p{p} h{h}/{kvh} d{d}",
            fn=decode_attn.paged_decode_attention,
            args=(sds((b, 1, h, d), dt), sds((nb, blk, kvh, d), dt),
                  sds((nb, blk, kvh, d), dt), sds((b,), jnp.int32),
                  sds((b, p), jnp.int32)),
            want=(((b, 1, h, d), "bfloat16"),),
        ))
    return cases


def _decode_int8_cases() -> list[OpCase]:
    """Int8 legs of BOTH decode-attention kernels: quantized K/V (+ f32
    absmax scales) in, q.dtype out, across the same (batch, seq, heads,
    pages) sweep as the full-width contracts — tileable kernel shapes AND
    the dense/gather fallbacks."""
    from distributed_llms_tpu.ops import decode_attn

    cases = []
    dt = jnp.bfloat16
    for b, s, h, kvh, d in [
        (1, 128, 4, 2, 128),   # kernel-tileable
        (2, 40, 4, 4, 64),     # untileable -> dense fallback
        (3, 384, 8, 2, 128),   # block stepdown
    ]:
        cases.append(OpCase(
            label=f"ragged b{b} s{s} h{h}/{kvh} d{d}",
            fn=lambda q, k, v, ln, ks, vs:
                decode_attn.ragged_decode_attention(
                    q, k, v, ln, k_scale=ks, v_scale=vs),
            args=(sds((b, 1, h, d), dt), sds((b, s, kvh, d), jnp.int8),
                  sds((b, s, kvh, d), jnp.int8), sds((b,), jnp.int32),
                  sds((b, s, kvh), jnp.float32),
                  sds((b, s, kvh), jnp.float32)),
            want=(((b, 1, h, d), "bfloat16"),),
        ))
    for b, nb, blk, p, h, kvh, d in [
        (1, 16, 8, 4, 4, 2, 128),    # kernel-tileable, page boundary
        (3, 8, 64, 2, 4, 4, 64),     # untileable d -> gather fallback
        (2, 32, 16, 8, 8, 2, 128),
    ]:
        cases.append(OpCase(
            label=f"paged b{b} nb{nb} blk{blk} p{p} h{h}/{kvh} d{d}",
            fn=lambda q, k, v, ln, tb, ks, vs:
                decode_attn.paged_decode_attention(
                    q, k, v, ln, tb, k_scale=ks, v_scale=vs),
            args=(sds((b, 1, h, d), dt), sds((nb, blk, kvh, d), jnp.int8),
                  sds((nb, blk, kvh, d), jnp.int8), sds((b,), jnp.int32),
                  sds((b, p), jnp.int32), sds((nb, blk, kvh), jnp.float32),
                  sds((nb, blk, kvh), jnp.float32)),
            want=(((b, 1, h, d), "bfloat16"),),
        ))
    return cases


def _decode_spmd_cases() -> list[OpCase]:
    """Per-SHARD shapes of the decode-attention SPMD rule (mesh-native
    paged serving): under `ops.decode_attn._ragged_spmd`/`_paged_spmd`
    each device runs the kernel on its local head slice — H and KVH both
    divided by tp, page table and cache width intact.  These cases trace
    exactly those local calls at tp2/tp4 slices of the full-head
    contracts, both legs, bf16 AND int8 — a head-slice shape the kernel
    cannot serve would mean the partition rule hands shards an illegal
    program."""
    from distributed_llms_tpu.ops import decode_attn

    cases = []
    dt = jnp.bfloat16
    # Ragged local shards: (tp, b, s, h, kvh, d).
    for tp, b, s, h, kvh, d in [(2, 2, 128, 8, 4, 128),
                                (4, 1, 256, 8, 4, 128)]:
        hl, kl = h // tp, kvh // tp
        cases.append(OpCase(
            label=f"ragged tp{tp} shard b{b} s{s} h{hl}/{kl} d{d}",
            fn=lambda q, k, v, ln: decode_attn.ragged_decode_attention(
                q, k, v, ln),
            args=(sds((b, 1, hl, d), dt), sds((b, s, kl, d), dt),
                  sds((b, s, kl, d), dt), sds((b,), jnp.int32)),
            want=(((b, 1, hl, d), "bfloat16"),),
        ))
        cases.append(OpCase(
            label=f"ragged-int8 tp{tp} shard b{b} s{s} h{hl}/{kl} d{d}",
            fn=lambda q, k, v, ln, ks, vs:
                decode_attn.ragged_decode_attention(
                    q, k, v, ln, k_scale=ks, v_scale=vs),
            args=(sds((b, 1, hl, d), dt), sds((b, s, kl, d), jnp.int8),
                  sds((b, s, kl, d), jnp.int8), sds((b,), jnp.int32),
                  sds((b, s, kl), jnp.float32), sds((b, s, kl), jnp.float32)),
            want=(((b, 1, hl, d), "bfloat16"),),
        ))
    # Paged local shards: (tp, b, nb, blk, p, h, kvh, d) — the pool's
    # page axes stay whole, only KVH slices.
    for tp, b, nb, blk, p, h, kvh, d in [(2, 2, 16, 8, 4, 8, 4, 128),
                                         (4, 1, 32, 16, 8, 8, 4, 128)]:
        hl, kl = h // tp, kvh // tp
        cases.append(OpCase(
            label=f"paged tp{tp} shard b{b} nb{nb} blk{blk} h{hl}/{kl}",
            fn=decode_attn.paged_decode_attention,
            args=(sds((b, 1, hl, d), dt), sds((nb, blk, kl, d), dt),
                  sds((nb, blk, kl, d), dt), sds((b,), jnp.int32),
                  sds((b, p), jnp.int32)),
            want=(((b, 1, hl, d), "bfloat16"),),
        ))
        cases.append(OpCase(
            label=f"paged-int8 tp{tp} shard b{b} nb{nb} blk{blk} h{hl}/{kl}",
            fn=lambda q, k, v, ln, tb, ks, vs:
                decode_attn.paged_decode_attention(
                    q, k, v, ln, tb, k_scale=ks, v_scale=vs),
            args=(sds((b, 1, hl, d), dt), sds((nb, blk, kl, d), jnp.int8),
                  sds((nb, blk, kl, d), jnp.int8), sds((b,), jnp.int32),
                  sds((b, p), jnp.int32), sds((nb, blk, kl), jnp.float32),
                  sds((nb, blk, kl), jnp.float32)),
            want=(((b, 1, hl, d), "bfloat16"),),
        ))
    return cases


def _quant_cases() -> list[OpCase]:
    import numpy as np

    from distributed_llms_tpu.checkpoint.quantize import quantize
    from distributed_llms_tpu.ops import quant_matmul

    cases = []
    rng = np.random.default_rng(0)
    for bits in (8, 4):
        w = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
        qt = quantize(w, bits=bits, block=32)
        m = 7  # non-power-of-two row count
        cases.append(OpCase(
            label=f"int{bits} k_lead1 m{m}",
            fn=functools.partial(
                lambda qt, x: quant_matmul.quant_contract(
                    x, qt, k_lead=1, eq="mk,kn->mn"), qt),
            args=(sds((m, 64), jnp.float32),),
            want=(((m, 128), "float32"),),
        ))
    return cases


def _forward_cases() -> list[OpCase]:
    from distributed_llms_tpu.models import model as model_lib

    cases = []
    # Plain forward across families: logits [B, T, V] ALWAYS float32
    # (unembed's preferred_element_type), whatever the param dtype.
    for pname in ("llama-tiny", "gpt2-tiny", "neox-tiny", "moe-tiny"):
        for b, t in [(1, 1), (2, 7), (3, 16)]:
            cfg = preset(pname, dtype="bfloat16")
            params = abstract_params(cfg)
            cases.append(OpCase(
                label=f"{pname} fwd b{b} t{t}",
                fn=functools.partial(
                    lambda cfg, p, tok: model_lib.forward(p, cfg, tok)[0],
                    cfg),
                args=(params, sds((b, t), jnp.int32)),
                want=(((b, t, cfg.vocab_size), "float32"),),
            ))
    # Cached per-row decode (the continuous batcher's step): cache dtype is
    # PRESERVED (kv_cache_dtype contract) and logits stay float32.
    cfg = preset("llama-tiny", dtype="bfloat16")
    params = abstract_params(cfg)
    for b, s in [(2, 32), (1, 64), (3, 48)]:
        cache = abstract_cache(cfg, b, s)
        l, kvh, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim_
        cases.append(OpCase(
            label=f"llama-tiny rowdecode b{b} s{s}",
            fn=functools.partial(
                lambda cfg, p, tok, pos, c, ci, m: (
                    lambda out: (out[0], out[1].k, out[1].v)
                )(model_lib.forward(
                    p, cfg, tok, positions=pos, cache=c, cache_index=ci,
                    attn_mask=m)), cfg),
            args=(params, sds((b, 1), jnp.int32), sds((b, 1), jnp.int32),
                  cache, sds((b,), jnp.int32),
                  sds((b, 1, 1, s), jnp.bool_)),
            want=(((b, 1, cfg.vocab_size), "float32"),
                  ((l, b, s, kvh, hd), "bfloat16"),
                  ((l, b, s, kvh, hd), "bfloat16")),
        ))
    # Paged decode through a page table: pool shapes round-trip unchanged.
    for b, nb, blk, p in [(2, 8, 8, 4), (1, 16, 8, 8)]:
        pool = abstract_pool(cfg, nb, blk)
        l, kvh, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim_
        cases.append(OpCase(
            label=f"llama-tiny pageddecode b{b} nb{nb} blk{blk}",
            fn=functools.partial(
                lambda cfg, prm, tok, pos, c, ci, tb: (
                    lambda out: (out[0], out[1].k, out[1].v)
                )(model_lib.forward(
                    prm, cfg, tok, positions=pos, cache=c, cache_index=ci,
                    kv_tables=tb)), cfg),
            args=(params, sds((b, 1), jnp.int32), sds((b, 1), jnp.int32),
                  pool, sds((b,), jnp.int32), sds((b, p), jnp.int32)),
            want=(((b, 1, cfg.vocab_size), "float32"),
                  ((l, nb, blk, kvh, hd), "bfloat16"),
                  ((l, nb, blk, kvh, hd), "bfloat16")),
        ))
    # Int8 paged decode (--kv-bits 8): the pool round-trips at int8 with
    # f32 scales — logits stay f32, nothing silently re-widens.
    for b, nb, blk, p in [(2, 8, 8, 4), (1, 16, 8, 8)]:
        qpool = abstract_quant_pool(cfg, nb, blk)
        l, kvh, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim_
        cases.append(OpCase(
            label=f"llama-tiny int8-pageddecode b{b} nb{nb} blk{blk}",
            fn=functools.partial(
                lambda cfg, prm, tok, pos, c, ci, tb: (
                    lambda out: (out[0], out[1].k, out[1].v,
                                 out[1].k_scale, out[1].v_scale)
                )(model_lib.forward(
                    prm, cfg, tok, positions=pos, cache=c, cache_index=ci,
                    kv_tables=tb)), cfg),
            args=(params, sds((b, 1), jnp.int32), sds((b, 1), jnp.int32),
                  qpool, sds((b,), jnp.int32), sds((b, p), jnp.int32)),
            want=(((b, 1, cfg.vocab_size), "float32"),
                  ((l, nb, blk, kvh, hd), "int8"),
                  ((l, nb, blk, kvh, hd), "int8"),
                  ((l, nb, blk, kvh), "float32"),
                  ((l, nb, blk, kvh), "float32")),
        ))
    return cases


def _kv_transfer_cases() -> list[OpCase]:
    """Disaggregated KV handoff: the export gather pulls a page run out of
    the pool into row layout ([L, 1, P*BLK, KVH, HD], pool dtype), and the
    import scatter adopts a page stack ([L, P, BLK, KVH, HD]) back into a
    pool whose shape/dtype must round-trip UNCHANGED — a widened pool or a
    silently-promoted dtype would corrupt every later admission."""
    import jax.numpy as jnp

    from distributed_llms_tpu.runtime import batcher as batcher_lib

    cfg = preset("llama-tiny", dtype="bfloat16")
    l, kvh, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim_
    cases = []
    # (pool pages, page size, pages in transit) incl. 1-page and
    # non-power-of-two transfers.
    for nb, blk, p in [(8, 8, 1), (16, 16, 3), (12, 8, 7)]:
        pool = abstract_pool(cfg, nb, blk)
        cases.append(OpCase(
            label=f"export gather nb{nb} blk{blk} p{p}",
            fn=batcher_lib._gather_row_pages,
            args=(pool, sds((p,), jnp.int32)),
            want=(((l, 1, p * blk, kvh, hd), "bfloat16"),
                  ((l, 1, p * blk, kvh, hd), "bfloat16")),
        ))
        cases.append(OpCase(
            label=f"import scatter nb{nb} blk{blk} p{p}",
            fn=lambda c, pl, k, v: (
                lambda out: (out.k, out.v)
            )(batcher_lib._import_pages(c, pl, k, v)),
            args=(pool, sds((p,), jnp.int32),
                  sds((l, p, blk, kvh, hd), jnp.float32),  # host payload
                  sds((l, p, blk, kvh, hd), jnp.float32)),
            want=(((l, nb, blk, kvh, hd), "bfloat16"),
                  ((l, nb, blk, kvh, hd), "bfloat16")),
        ))
    return cases


def _mixed_step_cases() -> list[OpCase]:
    """The fused mixed step's segment legs (the mixed-segment attention
    leg of ``schedule=mixed``): across prefill-bite buckets and
    contiguous/paged pools, the decode leg keeps [B, K] int32 tokens +
    [B, K] f32 logprobs, the prefill segment's transient row keeps its
    shape AND dtype (the continuation-mask attention must not widen it —
    the row splices into the shared cache at the finish), and the
    finishing-splice logits stay [1, V] f32."""
    import jax.numpy as jnp

    from distributed_llms_tpu.runtime import batcher as batcher_lib

    cfg = preset("llama-tiny", dtype="bfloat16")
    l, kvh, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim_
    v = cfg.vocab_size
    b, s, k = 4, 128, 8
    params = abstract_params(cfg)
    row = abstract_cache(cfg, 1, s)

    def pick(out):
        # (toks, lps, row_k', row_v', last_logits) — the fused step's
        # segment-leg outputs; the cache carry is pinned by the GC4
        # chaining contract and the decode_chunk GC1 cases already.
        return out[0], out[7], out[10], out[11], out[12]

    want = (((b, k), "int32"), ((b, k), "float32"),
            ((l, 1, s, kvh, hd), "bfloat16"),
            ((l, 1, s, kvh, hd), "bfloat16"),
            ((1, v), "float32"))
    cases = []
    for pw in (8, 32, 64):  # bite buckets up the shared ladder
        cases.append(OpCase(
            label=f"contiguous pw{pw}",
            fn=lambda p, c, lt, rl, va, ac, bu, rng, rk, rv, dn, pc, pl:
                pick(batcher_lib.mixed_step(
                    p, cfg, cfg, c, lt, rl, va, ac, bu, rng, k,
                    rk, rv, dn, pc, pl)),
            args=(params, abstract_cache(cfg, b, s), sds((b,), jnp.int32),
                  sds((b,), jnp.int32), sds((b, s), jnp.bool_),
                  sds((b,), jnp.bool_), sds((b,), jnp.int32), key_sds(),
                  row.k, row.v, sds((), jnp.int32),
                  sds((pw,), jnp.int32), sds((), jnp.int32)),
            want=want,
        ))
    nb, blk, p = 16, 16, 8  # pool pages, page size, pages per row (= s)
    for pw in (8, 64):
        cases.append(OpCase(
            label=f"paged pw{pw}",
            fn=lambda prm, c, lt, rl, va, ac, bu, rng, rk, rv, dn, pc, pl,
                tb:
                pick(batcher_lib.mixed_step(
                    prm, cfg, cfg, c, lt, rl, va, ac, bu, rng, k,
                    rk, rv, dn, pc, pl, tables=tb)),
            args=(params, abstract_pool(cfg, nb, blk), sds((b,), jnp.int32),
                  sds((b,), jnp.int32), sds((b, s), jnp.bool_),
                  sds((b,), jnp.bool_), sds((b,), jnp.int32), key_sds(),
                  row.k, row.v, sds((), jnp.int32),
                  sds((pw,), jnp.int32), sds((), jnp.int32),
                  sds((b, p), jnp.int32)),
            want=want,
        ))
    return cases


def _spec_chunk_paged_cases() -> list[OpCase]:
    """The paged speculative round (spec x paged tentpole): across spec_k
    values and BOTH pool widths, the round keeps [B, k+1] int32 tokens +
    f32 logprobs and [B] int32 commit counts (``commit_clamp``'s
    pos/length rollback output), the POOL leaves keep pool-storage dtypes
    (the scratch-tail window writes must not widen int8 data or f32
    scales), and the contiguous DRAFT cache keeps its dtype.  ``k_row``
    (the adaptive downshift) and the page tables are engaged in every
    case — the shapes the engine actually dispatches."""
    import jax.numpy as jnp

    from distributed_llms_tpu.runtime import batcher as batcher_lib

    cfg = preset("llama-tiny", dtype="bfloat16")
    l, kvh, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim_
    b, s, nb, blk, p = 2, 128, 16, 16, 8
    params = abstract_params(cfg)
    draft = abstract_cache(cfg, b, s)

    def pick(out):
        # (toks, m, lps, cache', draft_cache') — the carry vectors are
        # pinned by the GC4 chaining scenario; counts is None here.
        return out[0], out[1], out[2], out[3], out[4]

    cases = []
    for k in (2, 4):
        head = (((b, k + 1), "int32"), ((b,), "int32"),
                ((b, k + 1), "float32"))
        draft_want = (((l, b, s, kvh, hd), "bfloat16"),) * 2
        for kv_bits in (16, 8):
            if kv_bits == 8:
                pool = abstract_quant_pool(cfg, nb, blk)
                pool_want = (
                    ((l, nb, blk, kvh, hd), "int8"),
                    ((l, nb, blk, kvh, hd), "int8"),
                    ((l, nb, blk, kvh), "float32"),
                    ((l, nb, blk, kvh), "float32"),
                )
            else:
                pool = abstract_pool(cfg, nb, blk)
                pool_want = (((l, nb, blk, kvh, hd), "bfloat16"),) * 2
            cases.append(OpCase(
                label=f"k{k} kv{kv_bits}",
                fn=(lambda prm, dprm, c, dc, lt, rl, va, ac, bu, tb, kr,
                    _k=k:
                    pick(batcher_lib.spec_chunk(
                        prm, cfg, dprm, cfg, c, dc, lt, rl, va, ac, bu,
                        k=_k, tables=tb, k_row=kr))),
                args=(params, params, pool, draft, sds((b,), jnp.int32),
                      sds((b,), jnp.int32), sds((b, s), jnp.bool_),
                      sds((b,), jnp.bool_), sds((b,), jnp.int32),
                      sds((b, p), jnp.int32), sds((b,), jnp.int32)),
                want=head + pool_want + draft_want,
            ))
    return cases


def _sampling_cases() -> list[OpCase]:
    from distributed_llms_tpu.runtime import sampling

    cases = []
    for b, v in [(1, 256), (5, 1000)]:
        cases.append(OpCase(
            label=f"sample greedy b{b} v{v}",
            fn=functools.partial(
                lambda rng, lg: sampling.sample(rng, lg, 0.0)),
            args=(key_sds(), sds((b, v), jnp.float32)),
            want=(((b,), "int32"),),
        ))
        cases.append(OpCase(
            label=f"sample_rows b{b} v{v}",
            fn=lambda rng, lg, t, p, k: sampling.sample_rows(
                rng, lg, t, top_p=p, top_k_rows=k),
            args=(key_sds(), sds((b, v), jnp.float32),
                  sds((b,), jnp.float32), sds((b,), jnp.float32),
                  sds((b,), jnp.int32)),
            want=(((b,), "int32"),),
        ))
    return cases


def _constrain_cases() -> list[OpCase]:
    """Constraint mask ops (runtime/constrain.py): the per-row mask
    gather returns [B, V] float32 and the DFA advance returns [B] int32,
    over a (batch, states, vocab) sweep covering the byte-tokenizer and
    real-checkpoint vocab scales plus 1-state bias-only automata."""
    from distributed_llms_tpu.runtime import constrain

    cases = []
    for b, s, v in [(1, 1, 259), (4, 33, 512), (8, 300, 32000)]:
        cases.append(OpCase(
            label=f"gather_bias b{b} s{s} v{v}",
            fn=constrain.gather_bias,
            args=(sds((s, v), jnp.float32), sds((b,), jnp.int32)),
            want=(((b, v), "float32"),),
        ))
        cases.append(OpCase(
            label=f"advance_states b{b} s{s} v{v}",
            fn=constrain.advance_states,
            args=(sds((s, v), jnp.int32), sds((b,), jnp.int32),
                  sds((b,), jnp.int32)),
            want=(((b,), "int32"),),
        ))
    return cases


def op_contracts() -> list[OpContract]:
    return [
        OpContract("ops.flash.flash_attention", P_FLASH,
                   "out [B,Tq,H,D] in q.dtype across GQA/window/k_valid sweeps",
                   _flash_cases),
        OpContract("ops.ring.ring_attention", P_RING,
                   "out [B,T,H,D] under shard_map('seq') on fake meshes",
                   _ring_cases),
        OpContract("ops.ring.seq_cached_decode_attention", P_RING,
                   "psum-merged decode [B,1,H,D], replicated over 'seq'",
                   _seq_decode_cases),
        OpContract("ops.ulysses.ulysses_attention", P_ULYSSES,
                   "all-to-all head scatter round-trips to [B,T,H,D]",
                   _ulysses_cases),
        OpContract("ops.decode_attn.ragged_decode_attention", P_DECODE,
                   "[B,1,H,D] in q.dtype; tileable, stepdown, dense, window",
                   _ragged_cases),
        OpContract("ops.decode_attn.paged_decode_attention", P_DECODE,
                   "[B,1,H,D] through page tables incl. page-boundary sizes",
                   _paged_cases),
        OpContract("ops.decode_attn_int8", P_DECODE,
                   "int8 pages + absmax scales in, q.dtype out "
                   "(ragged + paged legs, kernel and fallback shapes)",
                   _decode_int8_cases),
        OpContract("ops.decode_attn_spmd", P_DECODE,
                   "per-shard head-slice shapes of the SPMD rule stay "
                   "legal (ragged + paged, bf16 + int8, tp2/tp4 slices)",
                   _decode_spmd_cases),
        OpContract("ops.quant_matmul.quant_contract", P_QMM,
                   "int8/int4 contraction keeps activation dtype and N axes",
                   _quant_cases),
        OpContract("models.model.forward", P_MODEL,
                   "logits f32, cache dtype preserved: plain/row-decode/paged",
                   _forward_cases),
        OpContract("runtime.sampling", P_SAMPLING,
                   "samplers return [B] int32 for static and per-row paths",
                   _sampling_cases),
        OpContract("runtime.constrain.mask_ops", P_CONSTRAIN,
                   "mask gather [B,V] f32 + DFA advance [B] i32 over a "
                   "batch/state/vocab sweep",
                   _constrain_cases),
        OpContract("batcher.kv_page_transfer", P_BATCHER,
                   "handoff export/import: pool shape+dtype round-trip, "
                   "payload cast to pool dtype",
                   _kv_transfer_cases),
        OpContract("batcher.mixed_step", P_BATCHER,
                   "fused mixed-segment legs: decode toks/lps shapes, "
                   "prefill row shape+dtype preserved, splice logits "
                   "[1,V] f32 (contiguous + paged, bite-bucket sweep)",
                   _mixed_step_cases),
        OpContract("batcher.spec_chunk_paged", P_BATCHER,
                   "paged speculative round: toks [B,k+1] i32 / commit "
                   "counts [B] i32 (the rollback clamp) / lps f32, pool "
                   "storage dtypes preserved (bf16 + int8-with-scales "
                   "scratch-tail page writes), draft cache dtype kept",
                   _spec_chunk_paged_cases),
    ]


# ---------------------------------------------------------------------------
# GC2 — sharding-spec audits
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SpecAudit:
    name: str       # "llama-tiny@tp4"
    path: str
    build: Callable[[], tuple]  # -> (param_tree, spec_tree, mesh)


@dataclass(frozen=True)
class CollectiveAudit:
    name: str
    path: str
    doc: str
    build: Callable[[], tuple]  # -> (fn, args, mesh)


MESH_LADDER: tuple[tuple[str, dict], ...] = (
    ("tp2", dict(model=2)),
    ("tp4", dict(model=4)),
    ("tp8", dict(model=8)),
    ("pp2", dict(pipe=2)),
    ("pp2tp4", dict(pipe=2, model=4)),
    ("ep2tp2", dict(expert=2, model=2)),
)


def spec_audits() -> list[SpecAudit]:
    from distributed_llms_tpu.models.presets import PRESETS

    out = []
    for pname in sorted(PRESETS):
        for mlabel, axes in MESH_LADDER:
            def build(pname=pname, axes=axes):
                from distributed_llms_tpu.parallel import specs as specs_lib

                cfg = preset(pname)
                mesh = fake_mesh(**axes)
                return (abstract_params(cfg),
                        specs_lib.param_specs(cfg, mesh), mesh)

            out.append(SpecAudit(f"{pname}@{mlabel}", P_SPECS, build))
    # Staged (pipelined) tree: blocks reshaped [L,...] -> [P, L/P, ...] must
    # structure-match staged_param_specs on a divisible preset.
    def build_staged():
        from distributed_llms_tpu.parallel import api as api_lib
        from distributed_llms_tpu.parallel import pipeline as pipeline_lib

        cfg = preset("llama-tiny")
        mesh = fake_mesh(pipe=2)
        tree = dict(abstract_params(cfg))
        tree["blocks"] = jax.eval_shape(
            lambda b: pipeline_lib.split_stages(b, 2), tree["blocks"]
        )
        return tree, api_lib.staged_param_specs(cfg, mesh), mesh

    out.append(SpecAudit("llama-tiny@staged-pp2",
                         "distributed_llms_tpu/parallel/api.py",
                         build_staged))
    out += _page_pool_audits()
    out += _decode_spmd_audits()
    return out


_MESH_PAGED_LADDER: tuple[tuple[str, dict], ...] = (
    ("tp2", dict(model=2)),
    ("tp4", dict(model=4)),
    ("dp2tp2", dict(data=2, model=2)),
)


def _page_pool_audits() -> list[SpecAudit]:
    """Sharded page-pool layout (mesh-native paged serving): the pool
    trees `_paged_pool` builds must structure-match
    `parallel.specs.page_pool_specs` — KV heads over 'model', int8 absmax
    scales sharded with their pages — with axis names and divisibility
    checked over the tp ladder.  llama-tiny (2 KV heads) exercises the
    non-divisible degrade at tp4; gpt2-tiny (4 heads) shards at both."""
    out = []
    for pname in ("llama-tiny", "gpt2-tiny"):
        for mlabel, axes in _MESH_PAGED_LADDER:
            for bits in (16, 8):
                def build(pname=pname, axes=axes, bits=bits):
                    from distributed_llms_tpu.parallel import (
                        specs as specs_lib,
                    )

                    cfg = preset(pname)
                    mesh = fake_mesh(**axes)
                    pool = (abstract_quant_pool if bits == 8
                            else abstract_pool)(cfg, 16, 16)
                    return pool, specs_lib.page_pool_specs(
                        cfg, mesh, kv_bits=bits), mesh

                out.append(SpecAudit(
                    f"page-pool[kv{bits}|{pname}]@{mlabel}", P_SPECS, build
                ))
    return out


def _decode_spmd_audits() -> list[SpecAudit]:
    """The decode-attention SPMD rule's operand placement
    (`ops.decode_attn.spmd_operand_specs` — built on the SAME axis
    resolver the custom_partitioning lowering runs): every operand spec
    must name real mesh axes and divide its dims over the ladder, for
    the ragged and paged legs at both KV widths."""
    out = []
    b, s, h, kvh, d = 4, 128, 8, 4, 128
    nb, blk, p = 16, 16, 8
    for mlabel, axes in _MESH_PAGED_LADDER:
        for paged in (False, True):
            for quant in (False, True):
                def build(axes=axes, paged=paged, quant=quant):
                    from distributed_llms_tpu.ops import decode_attn

                    mesh = fake_mesh(**axes)
                    kv_shape = (nb, blk, kvh, d) if paged else (b, s, kvh, d)
                    kv_dt = jnp.int8 if quant else jnp.bfloat16
                    tree = {"q": sds((b, 1, h, d), jnp.bfloat16),
                            "lengths": sds((b,), jnp.int32)}
                    if paged:
                        tree["k_pages"] = sds(kv_shape, kv_dt)
                        tree["v_pages"] = sds(kv_shape, kv_dt)
                        tree["tables"] = sds((b, p), jnp.int32)
                    else:
                        tree["k"] = sds(kv_shape, kv_dt)
                        tree["v"] = sds(kv_shape, kv_dt)
                    if quant:
                        scale_shape = kv_shape[:-1]
                        tree["k_scale"] = sds(scale_shape, jnp.float32)
                        tree["v_scale"] = sds(scale_shape, jnp.float32)
                    specs, _ = decode_attn.spmd_operand_specs(
                        mesh, (b, 1, h, d), kv_shape, paged=paged,
                        quant=quant,
                    )
                    return tree, specs, mesh

                leg = "paged" if paged else "ragged"
                bits = "int8" if quant else "bf16"
                out.append(SpecAudit(
                    f"decode-attn-spmd[{leg}|{bits}]@{mlabel}", P_DECODE,
                    build,
                ))
    return out


def collective_audits() -> list[CollectiveAudit]:
    audits = []

    def build_ring():
        case = _ring_cases()[1]  # seq4 f32
        return case.fn, case.args, fake_mesh(seq=4)

    def build_ring_decode():
        case = _seq_decode_cases()[0]  # seq2
        return case.fn, case.args, fake_mesh(seq=2)

    def build_ulysses():
        case = _ulysses_cases()[1]  # seq4
        return case.fn, case.args, fake_mesh(seq=4)

    audits.append(CollectiveAudit(
        "ops.ring.ring_attention", P_RING,
        "ppermute rotation rides the mesh's 'seq' axis", build_ring))
    audits.append(CollectiveAudit(
        "ops.ring.seq_cached_decode_attention", P_RING,
        "pmax/psum stat merge over 'seq'", build_ring_decode))
    audits.append(CollectiveAudit(
        "ops.ulysses.ulysses_attention", P_ULYSSES,
        "all_to_all/all_gather over 'seq'", build_ulysses))
    return audits


# ---------------------------------------------------------------------------
# GC3 — dtype-promotion contracts
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class HotFnContract:
    name: str
    path: str
    doc: str
    build: Callable[[], tuple]      # -> (fn, args)
    allow_upcast: frozenset = frozenset()  # function names allowed bf16->f32


# Deliberate f32-stability upcasts in the model stack: norms compute in
# f32, RoPE builds its rotation table in f32, the MoE router softmaxes in
# f32.  Anything ELSE converting bf16 activations up is an accidental
# double-width HBM bill and fails GC302.
MODEL_UPCAST_ALLOW = frozenset(
    {"rms_norm", "layer_norm", "apply_rope", "moe_swiglu"}
)


def hot_contracts() -> list[HotFnContract]:
    from distributed_llms_tpu.models import model as model_lib

    out = []
    for pname in ("llama-tiny", "gpt2-tiny", "neox-tiny", "moe-tiny"):
        def build_fwd(pname=pname):
            cfg = preset(pname, dtype="bfloat16")
            return (
                functools.partial(
                    lambda cfg, p, t: model_lib.forward(p, cfg, t)[0], cfg),
                (abstract_params(cfg), sds((2, 8), jnp.int32)),
            )

        out.append(HotFnContract(
            f"models.model.forward[{pname}]", P_MODEL,
            "bf16 prefill upcasts only in norm/rope/router",
            build_fwd, MODEL_UPCAST_ALLOW))

    def build_decode():
        cfg = preset("llama-tiny", dtype="bfloat16")
        cache = abstract_cache(cfg, 2, 32)
        return (
            functools.partial(
                lambda cfg, p, t, pos, c, ci, m: model_lib.forward(
                    p, cfg, t, positions=pos, cache=c, cache_index=ci,
                    attn_mask=m)[0], cfg),
            (abstract_params(cfg), sds((2, 1), jnp.int32),
             sds((2, 1), jnp.int32), cache, sds((2,), jnp.int32),
             sds((2, 1, 1, 32), jnp.bool_)),
        )

    out.append(HotFnContract(
        "models.model.forward[row-decode]", P_MODEL,
        "bf16 cached decode step stays bf16 outside norm/rope",
        build_decode, MODEL_UPCAST_ALLOW))

    def build_sampling():
        from distributed_llms_tpu.runtime import sampling

        return (
            lambda rng, lg, t, p, k: sampling.sample_rows(
                rng, lg, t, top_p=p, top_k_rows=k),
            (key_sds(), sds((4, 512), jnp.float32), sds((4,), jnp.float32),
             sds((4,), jnp.float32), sds((4,), jnp.int32)),
        )

    out.append(HotFnContract(
        "runtime.sampling.sample_rows", P_SAMPLING,
        "no float64 anywhere in the per-row sampler",
        build_sampling, frozenset()))
    return out


# ---------------------------------------------------------------------------
# GC4 — recompilation scenarios
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class RecompileScenario:
    name: str
    path: str
    doc: str
    ladder: tuple[int, ...]             # raw request lengths swept
    width_of: Callable[[int], int]      # raw length -> jit-visible width
    allowed_widths: tuple[int, ...]     # the CLOSED ladder (GC402)
    max_keys: int                       # declared compile-key bound (GC401)
    trace: Callable[[int], str]         # width -> compile-cache key


_GC4_LADDER = (1, 2, 3, 5, 7, 8, 9, 13, 16, 17, 24, 31, 32, 33, 47, 63,
               64, 65, 100, 120)


def recompile_scenarios() -> list[RecompileScenario]:
    from distributed_llms_tpu.runtime import shapes as shapes_lib

    from .core import jaxpr_hash

    out = []
    s_cap = 128  # tiny-config cache width the sweeps run against
    cfg = preset("llama-tiny")

    # -- batcher admission: prompt widths must walk the shared ladder, and
    # each distinct width is ONE compiled program.
    def admit_trace(width: int) -> str:
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        params = abstract_params(cfg)
        cache = abstract_cache(cfg, 4, s_cap)
        return jaxpr_hash(
            lambda p, c, slot, prompt, plen, rng: batcher_lib.admit_row(
                p, cfg, c, slot, prompt, plen, rng),
            params, cache, sds((), jnp.int32), sds((width,), jnp.int32),
            sds((), jnp.int32), key_sds(),
            statics={"cfg": cfg},
        )

    out.append(RecompileScenario(
        name="batcher.admit_row", path=P_BATCHER,
        doc="admission prefill compiles once per prompt bucket",
        ladder=_GC4_LADDER,
        width_of=lambda n: min(shapes_lib.bucket_length(n), s_cap),
        allowed_widths=tuple(shapes_lib.bucket_ladder(s_cap)),
        max_keys=shapes_lib.bucket_count(s_cap),
        trace=admit_trace,
    ))

    # -- decode step: shapes are depth-independent, so the WHOLE ladder is
    # one compile key (depths are traced values, not shapes).
    def decode_trace(width: int) -> str:
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        b = 4
        params = abstract_params(cfg)
        cache = abstract_cache(cfg, b, s_cap)
        return jaxpr_hash(
            lambda p, c, lt, rl, va, ac, bu, rng: batcher_lib.decode_chunk(
                p, cfg, c, lt, rl, va, ac, bu, rng, chunk_steps=8),
            params, cache, sds((b,), jnp.int32), sds((b,), jnp.int32),
            sds((b, s_cap), jnp.bool_), sds((b,), jnp.bool_),
            sds((b,), jnp.int32), key_sds(),
            statics={"cfg": cfg, "chunk_steps": 8},
        )

    out.append(RecompileScenario(
        name="batcher.decode_chunk", path=P_BATCHER,
        doc="decode chunk is ONE program across every resident depth",
        ladder=_GC4_LADDER,
        width_of=lambda n: s_cap,
        allowed_widths=(s_cap,),
        max_keys=1,
        trace=decode_trace,
    ))

    # -- int8 paged decode step: the quantized leg (per-step KV quantize
    # + scale-fused attention read) must still be ONE compiled program —
    # neither depths nor page contents are shapes.
    def decode_int8_trace(width: int) -> str:
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        b, nb, blk, p = 4, 16, 16, 8
        params = abstract_params(cfg)
        pool = abstract_quant_pool(cfg, nb, blk)
        return jaxpr_hash(
            lambda prm, c, lt, rl, va, ac, bu, rng, tb:
                batcher_lib.decode_chunk(
                    prm, cfg, c, lt, rl, va, ac, bu, rng, chunk_steps=8,
                    tables=tb),
            params, pool, sds((b,), jnp.int32), sds((b,), jnp.int32),
            sds((b, p * blk), jnp.bool_), sds((b,), jnp.bool_),
            sds((b,), jnp.int32), key_sds(), sds((b, p), jnp.int32),
            statics={"cfg": cfg, "chunk_steps": 8},
        )

    out.append(RecompileScenario(
        name="batcher.decode_chunk_int8", path=P_BATCHER,
        doc="int8 paged decode (quantized write + scale-fused read) "
            "stays ONE program across every resident depth",
        ladder=_GC4_LADDER,
        width_of=lambda n: s_cap,
        allowed_widths=(s_cap,),
        max_keys=1,
        trace=decode_int8_trace,
    ))

    # -- dispatch-ahead (overlapped) decode: the engine loop chains the
    # carry from one chunk's outputs straight into the next call, with
    # the per-row sampling + penalty-histogram kwargs engaged for the
    # whole span.  That steady-state program must be ONE compile key
    # across every resident depth — a second key would mean the chained
    # dispatch pays a trace on the engine thread mid-span, serializing
    # exactly the window the overlap exists to hide.
    def decode_overlap_trace(width: int) -> str:
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        b = 4
        params = abstract_params(cfg)
        cache = abstract_cache(cfg, b, s_cap)
        return jaxpr_hash(
            lambda p, c, lt, rl, va, ac, bu, rng, tr, pr, kr, cnt, prr, frr:
                batcher_lib.decode_chunk(
                    p, cfg, c, lt, rl, va, ac, bu, rng, chunk_steps=8,
                    temp_row=tr, topp_row=pr, topk_row=kr, counts=cnt,
                    pres_row=prr, freq_row=frr),
            params, cache, sds((b,), jnp.int32), sds((b,), jnp.int32),
            sds((b, s_cap), jnp.bool_), sds((b,), jnp.bool_),
            sds((b,), jnp.int32), key_sds(),
            sds((b,), jnp.float32), sds((b,), jnp.float32),
            sds((b,), jnp.int32), sds((b, cfg.vocab_size), jnp.int32),
            sds((b,), jnp.float32), sds((b,), jnp.float32),
            statics={"cfg": cfg, "chunk_steps": 8},
        )

    out.append(RecompileScenario(
        name="batcher.decode_chunk_overlap", path=P_BATCHER,
        doc="dispatch-ahead decode (carry chained from the previous "
            "chunk, per-row sampling + penalties engaged) stays ONE "
            "program across every resident depth",
        ladder=_GC4_LADDER,
        width_of=lambda n: s_cap,
        allowed_widths=(s_cap,),
        max_keys=1,
        trace=decode_overlap_trace,
    ))

    # -- constrained decode: mixed constrained+free rows (the token-mask
    # stack + per-row automaton states + per-row sampling engaged, as
    # runtime/batcher._span_plan builds it) must still be ONE compiled
    # program across every resident depth — the mask is a traced gather,
    # the DFA advance a traced scatter-free lookup, and the state carry
    # chains device-resident through dispatch-ahead chunks.
    def decode_constrained_trace(width: int) -> str:
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        b, n_states = 4, 32
        params = abstract_params(cfg)
        cache = abstract_cache(cfg, b, s_cap)
        return jaxpr_hash(
            lambda p, c, lt, rl, va, ac, bu, rng, tr, ms, ns, ds:
                batcher_lib.decode_chunk(
                    p, cfg, c, lt, rl, va, ac, bu, rng, chunk_steps=8,
                    temp_row=tr, mask_stack=ms, next_stack=ns,
                    dfa_state=ds),
            params, cache, sds((b,), jnp.int32), sds((b,), jnp.int32),
            sds((b, s_cap), jnp.bool_), sds((b,), jnp.bool_),
            sds((b,), jnp.int32), key_sds(),
            sds((b,), jnp.float32),
            sds((n_states, cfg.vocab_size), jnp.float32),
            sds((n_states, cfg.vocab_size), jnp.int32),
            sds((b,), jnp.int32),
            statics={"cfg": cfg, "chunk_steps": 8},
        )

    out.append(RecompileScenario(
        name="batcher.decode_chunk_constrained", path=P_BATCHER,
        doc="mixed constrained+free decode (token-mask stack, per-row "
            "DFA states, per-row sampling engaged) stays ONE program "
            "across every resident depth",
        ladder=_GC4_LADDER,
        width_of=lambda n: s_cap,
        allowed_widths=(s_cap,),
        max_keys=1,
        trace=decode_constrained_trace,
    ))

    # -- fused mixed step (schedule=mixed): the K-step decode scan AND
    # the head pending prefill's bite in ONE compiled program.  The
    # prefill leg's width is pinned to a single policy-sized bucket
    # (batcher._mixed_width), so the whole prefill-mix ladder — any bite
    # length, any live-row count, any resident depth (all traced values,
    # never shapes) — must land on EXACTLY one compile key: a second key
    # would mean a fused dispatch pays an XLA trace on the engine thread
    # mid-span, serializing exactly the stall the mixed schedule removes.
    def mixed_step_trace(width: int) -> str:
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        b, pw = 4, 32  # pw: the policy's fixed prefill-leg bucket
        params = abstract_params(cfg)
        cache = abstract_cache(cfg, b, s_cap)
        row = abstract_cache(cfg, 1, s_cap)
        return jaxpr_hash(
            lambda p, c, lt, rl, va, ac, bu, rng, rk, rv, dn, pc, pl:
                batcher_lib.mixed_step(
                    p, cfg, cfg, c, lt, rl, va, ac, bu, rng, 8,
                    rk, rv, dn, pc, pl),
            params, cache, sds((b,), jnp.int32), sds((b,), jnp.int32),
            sds((b, s_cap), jnp.bool_), sds((b,), jnp.bool_),
            sds((b,), jnp.int32), key_sds(),
            row.k, row.v, sds((), jnp.int32),
            sds((pw,), jnp.int32), sds((), jnp.int32),
            statics={"cfg": cfg, "pcfg": cfg, "chunk_steps": 8},
        )

    out.append(RecompileScenario(
        name="batcher.mixed_step", path=P_BATCHER,
        doc="fused token-budget step (decode scan + prefill bite, "
            "schedule=mixed) stays ONE program across the whole "
            "prefill-mix ladder",
        ladder=_GC4_LADDER,
        width_of=lambda n: s_cap,
        allowed_widths=(s_cap,),
        max_keys=1,
        trace=mixed_step_trace,
    ))

    # -- paged speculative round (spec x paged tentpole): the draft scan,
    # the (k+1)-token paged verify window (scratch-tail page writes +
    # per-offset prefix reads), the rollback clamp, AND the adaptive
    # k_row downshift are ONE compiled program — depths, page tables,
    # per-row clamp values, and row mixes are all traced values, never
    # shapes.  A second key would mean a downshift (or a new resident
    # depth) pays an XLA trace on the engine thread mid-span — the
    # ladder of k_row values the scheduler emits must be compile-free.
    def spec_paged_trace(width: int) -> str:
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        b, nb, blk, p = 4, 16, 16, 8
        params = abstract_params(cfg)
        pool = abstract_pool(cfg, nb, blk)
        draft = abstract_cache(cfg, b, s_cap)
        return jaxpr_hash(
            lambda prm, dprm, c, dc, lt, rl, va, ac, bu, tb, kr, prr, frr,
            cnt:
                batcher_lib.spec_chunk(
                    prm, cfg, dprm, cfg, c, dc, lt, rl, va, ac, bu, k=4,
                    tables=tb, k_row=kr, pres_row=prr, freq_row=frr,
                    counts=cnt),
            params, params, pool, draft, sds((b,), jnp.int32),
            sds((b,), jnp.int32), sds((b, s_cap), jnp.bool_),
            sds((b,), jnp.bool_), sds((b,), jnp.int32),
            sds((b, p), jnp.int32), sds((b,), jnp.int32),
            sds((b,), jnp.float32), sds((b,), jnp.float32),
            sds((b, cfg.vocab_size), jnp.int32),
            statics={"cfg": cfg, "draft_cfg": cfg, "k": 4},
        )

    out.append(RecompileScenario(
        name="batcher.spec_chunk_paged", path=P_BATCHER,
        doc="paged draft/verify round (page tables, adaptive k_row, "
            "penalties engaged) stays ONE program across the spec_k "
            "ladder, every resident depth, and every row mix",
        ladder=_GC4_LADDER,
        width_of=lambda n: s_cap,
        allowed_widths=(s_cap,),
        max_keys=1,
        trace=spec_paged_trace,
    ))

    # -- whole-batch generate: the engine pads T up the ladder under the
    # sequence budget; every padded width is one compile key.
    n_new, limit = 8, s_cap

    def generate_trace(width: int) -> str:
        from distributed_llms_tpu.runtime import generate as gen_lib

        params = abstract_params(cfg)
        return jaxpr_hash(
            lambda p, prompt, lens, rng: gen_lib.generate_tokens(
                p, cfg, prompt, lens, rng, max_new_tokens=n_new),
            params, sds((2, width), jnp.int32), sds((2,), jnp.int32),
            key_sds(),
            statics={"cfg": cfg, "max_new_tokens": n_new},
        )

    out.append(RecompileScenario(
        name="engine.generate_tokens", path=P_ENGINE,
        doc="whole-batch generate pads T up the ladder (budget-capped)",
        ladder=tuple(n for n in _GC4_LADDER if n <= limit - n_new),
        width_of=lambda n: shapes_lib.generate_pad_len(n, n_new, limit),
        allowed_widths=tuple(shapes_lib.bucket_ladder(limit - n_new)),
        max_keys=shapes_lib.bucket_count(limit - n_new),
        trace=generate_trace,
    ))
    return out


# ---------------------------------------------------------------------------
# GC5 — donation contracts
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class DonationContract:
    name: str
    path: str
    doc: str
    build: Callable[[], tuple]   # -> (jitted_fn, [(argname, value), ...], kwargs)
    must_donate: tuple[str, ...]
    may_keep: tuple[str, ...] = ()   # argnames allowed large + non-donated
    static_args: tuple[str, ...] = ("cfg",)  # dropped from Lowered.args_info
    min_bytes: int = 128 * 1024      # "large" threshold for GC502


def donation_contracts() -> list[DonationContract]:
    cfg = preset("llama-tiny")
    out = []

    def build_admit():
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        return (batcher_lib.admit_row, [
            ("params", abstract_params(cfg)), ("cfg", cfg),
            ("cache", abstract_cache(cfg, 4, 128)),
            ("slot", sds((), jnp.int32)), ("prompt", sds((16,), jnp.int32)),
            ("plen", sds((), jnp.int32)), ("rng", key_sds()),
        ], {})

    out.append(DonationContract(
        "batcher.admit_row", P_BATCHER,
        "admission splices in place: the shared KV cache is donated",
        build_admit, must_donate=("cache",), may_keep=("params",)))

    def build_decode():
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        b = 4
        return (batcher_lib.decode_chunk, [
            ("params", abstract_params(cfg)), ("cfg", cfg),
            ("cache", abstract_cache(cfg, b, 128)),
            ("last_tok", sds((b,), jnp.int32)),
            ("real_lens", sds((b,), jnp.int32)),
            ("valid", sds((b, 128), jnp.bool_)),
            ("active", sds((b,), jnp.bool_)),
            ("budget", sds((b,), jnp.int32)), ("rng", key_sds()),
        ], {"chunk_steps": 8})

    out.append(DonationContract(
        "batcher.decode_chunk", P_BATCHER,
        "the decode carry (KV cache) never copies between chunks",
        build_decode, must_donate=("cache",), may_keep=("params",)))

    def build_admit_paged():
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        return (batcher_lib.admit_row_paged, [
            ("params", abstract_params(cfg)), ("cfg", cfg),
            ("cache", abstract_pool(cfg, 32, 16)),
            ("page_list", sds((8,), jnp.int32)),
            ("prompt", sds((16,), jnp.int32)), ("plen", sds((), jnp.int32)),
            ("rng", key_sds()),
        ], {})

    out.append(DonationContract(
        "batcher.admit_row_paged", P_BATCHER,
        "paged admission scatters into a donated pool",
        build_admit_paged, must_donate=("cache",), may_keep=("params",)))

    def build_auto_paged():
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        return (batcher_lib.admit_row_auto_paged, [
            ("params", abstract_params(cfg)), ("cfg", cfg),
            ("cache", abstract_pool(cfg, 32, 16)),
            ("read_list", sds((8,), jnp.int32)),
            ("write_list", sds((8,), jnp.int32)),
            ("prefix_len", sds((), jnp.int32)),
            ("chunk", sds((16,), jnp.int32)), ("clen", sds((), jnp.int32)),
            ("rng", key_sds()),
        ], {})

    out.append(DonationContract(
        "batcher.admit_row_auto_paged", P_BATCHER,
        "prefix-cache-hit admission gathers then scatters one donated pool",
        build_auto_paged, must_donate=("cache",), may_keep=("params",)))

    def build_chunk_step():
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        l, kvh, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim_
        row = sds((l, 1, 256, kvh, hd), jnp.float32)
        return (batcher_lib.prefill_chunk_step, [
            ("params", abstract_params(cfg)), ("cfg", cfg),
            ("row_k", row), ("row_v", row), ("done", sds((), jnp.int32)),
            ("chunk", sds((32,), jnp.int32)), ("clen", sds((), jnp.int32)),
        ], {})

    out.append(DonationContract(
        "batcher.prefill_chunk_step", P_BATCHER,
        "chunked prefill updates the transient row KV in place",
        build_chunk_step, must_donate=("row_k", "row_v"),
        may_keep=("params",)))

    def build_spec_chunk():
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        b, s = 2, 128
        return (batcher_lib.spec_chunk, [
            ("params", abstract_params(cfg)), ("cfg", cfg),
            ("draft_params", abstract_params(cfg)), ("draft_cfg", cfg),
            ("cache", abstract_cache(cfg, b, s)),
            ("draft_cache", abstract_cache(cfg, b, s)),
            ("last_tok", sds((b,), jnp.int32)),
            ("real_lens", sds((b,), jnp.int32)),
            ("valid", sds((b, s), jnp.bool_)),
            ("active", sds((b,), jnp.bool_)),
            ("budget", sds((b,), jnp.int32)),
        ], {"k": 3})

    out.append(DonationContract(
        "batcher.spec_chunk", P_BATCHER,
        "speculative round donates BOTH target and draft caches",
        build_spec_chunk, must_donate=("cache", "draft_cache"),
        may_keep=("params", "draft_params"),
        static_args=("cfg", "draft_cfg")))

    def build_spec_chunk_paged():
        from distributed_llms_tpu.runtime import batcher as batcher_lib

        b, s, nb, blk, p = 2, 128, 16, 16, 8
        return (batcher_lib.spec_chunk, [
            ("params", abstract_params(cfg)), ("cfg", cfg),
            ("draft_params", abstract_params(cfg)), ("draft_cfg", cfg),
            ("cache", abstract_pool(cfg, nb, blk)),
            ("draft_cache", abstract_cache(cfg, b, s)),
            ("last_tok", sds((b,), jnp.int32)),
            ("real_lens", sds((b,), jnp.int32)),
            ("valid", sds((b, s), jnp.bool_)),
            ("active", sds((b,), jnp.bool_)),
            ("budget", sds((b,), jnp.int32)),
        ], {"k": 3, "tables": sds((b, p), jnp.int32),
            "k_row": sds((b,), jnp.int32)})

    out.append(DonationContract(
        "batcher.spec_chunk_paged", P_BATCHER,
        "paged speculative round donates the pool and the draft cache "
        "(tables/k_row ride as read-only inputs)",
        build_spec_chunk_paged, must_donate=("cache", "draft_cache"),
        may_keep=("params", "draft_params"),
        static_args=("cfg", "draft_cfg")))
    return out


# ---------------------------------------------------------------------------
# README table (--write-docs)
# ---------------------------------------------------------------------------

DOC_BEGIN = "<!-- graftcheck:contracts:begin -->"
DOC_END = "<!-- graftcheck:contracts:end -->"


def contracts_table() -> str:
    """Markdown table of every registered contract, grouped by family."""
    rows = ["| family | contract | pins |", "|---|---|---|"]
    for c in op_contracts():
        rows.append(f"| GC1 | `{c.name}` | {c.doc} |")
    presets = sorted({a.name.split("@")[0] for a in spec_audits()
                      if "[" not in a.name})
    meshes = ", ".join(label for label, _ in MESH_LADDER)
    rows.append(
        f"| GC2 | `parallel.specs.param_specs` | tree structure, axis "
        f"names, rank, divisibility over {len(presets)} presets x "
        f"({meshes}) + staged blocks |"
    )
    paged_meshes = ", ".join(label for label, _ in _MESH_PAGED_LADDER)
    rows.append(
        f"| GC2 | `parallel.specs.page_pool_specs` | sharded page-pool "
        f"layout (KV heads over 'model'; int8 scales shard with their "
        f"pages) over {{kv16, kv8}} x ({paged_meshes}) |"
    )
    rows.append(
        f"| GC2 | `ops.decode_attn.spmd_operand_specs` | decode-attn "
        f"SPMD rule operand placement (ragged + paged, bf16 + int8) "
        f"over ({paged_meshes}) |"
    )
    for a in collective_audits():
        rows.append(f"| GC2 | `{a.name}` | {a.doc} |")
    for h in hot_contracts():
        rows.append(f"| GC3 | `{h.name}` | {h.doc} |")
    for s in recompile_scenarios():
        rows.append(
            f"| GC4 | `{s.name}` | {s.doc} (<= {s.max_keys} compile keys) |"
        )
    for d in donation_contracts():
        rows.append(f"| GC5 | `{d.name}` | {d.doc} |")
    return "\n".join(rows)
