"""GC3 — dtype-promotion lint over hot-function jaxprs.

Two accident classes, both invisible in source review and both caught here
by walking the traced jaxpr:

- GC301 float64 anywhere: with x64 enabled (a stray env flag, a
  ``np.float64`` constant) a hot function silently doubles its FLOPs and
  HBM.  Any f64/c128 aval in the trace fails.
- GC302 unallowlisted bf16->f32 upcast: a ``convert_element_type`` whose
  input is bf16 and output f32 doubles the bandwidth of whatever consumes
  it.  Deliberate stability upcasts (norms, RoPE tables, routers) are
  allowlisted BY FUNCTION NAME — the eqn's source attribution
  (``source_info_util.user_frame``) must land in the contract's
  ``allow_upcast`` set, so a new upcast in new code fails even when old
  ones stay blessed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .core import Finding, walk_eqns

try:
    from jax._src import source_info_util
except Exception:  # pragma: no cover - internal layout moved
    source_info_util = None


_WIDE = {jnp.dtype("float64"), jnp.dtype("complex128")}


def _frame_of(eqn) -> tuple[str, str]:
    if source_info_util is None:
        return ("?", "?")
    frame = source_info_util.user_frame(eqn.source_info)
    if frame is None:
        return ("?", "?")
    return (frame.file_name.rsplit("/", 1)[-1], frame.function_name)


def _avals(eqn):
    for v in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "dtype"):
            yield aval


def check(contracts=None) -> list[Finding]:
    if contracts is None:
        from .contracts import hot_contracts

        contracts = hot_contracts()
    findings: list[Finding] = []
    for contract in contracts:
        try:
            fn, args = contract.build()
            jaxpr = jax.make_jaxpr(fn)(*args)
        except Exception as exc:
            findings.append(Finding(
                "GC301", contract.path, 0,
                f"{contract.name}: hot function failed to trace: "
                f"{type(exc).__name__}: {str(exc).splitlines()[0][:160]}"))
            continue
        wide_sites: set[tuple[str, str]] = set()
        upcast_sites: set[tuple[str, str]] = set()
        for eqn in walk_eqns(jaxpr):
            for aval in _avals(eqn):
                if aval.dtype in _WIDE:
                    wide_sites.add(_frame_of(eqn))
                    break
            if (source_info_util is not None
                    and eqn.primitive.name == "convert_element_type"
                    and eqn.outvars[0].aval.dtype == jnp.float32
                    and any(getattr(v, "aval", None) is not None
                            and getattr(v.aval, "dtype", None) == jnp.bfloat16
                            for v in eqn.invars)):
                # Source attribution IS the allowlist mechanism: without
                # source_info_util (internal jax layout moved) GC302 must
                # SKIP, not flag every deliberate upcast as "? (?)".
                site = _frame_of(eqn)
                if site[1] not in contract.allow_upcast:
                    upcast_sites.add(site)
        for fname, func in sorted(wide_sites):
            findings.append(Finding(
                "GC301", contract.path, 0,
                f"{contract.name}: float64 reaches the trace via "
                f"{func} ({fname})"))
        for fname, func in sorted(upcast_sites):
            findings.append(Finding(
                "GC302", contract.path, 0,
                f"{contract.name}: bf16->f32 upcast in {func} ({fname}) "
                f"is not in the allowlist "
                f"{sorted(contract.allow_upcast) or '[]'}"))
    return findings
