"""GC4 — recompilation-hazard detector.

The "recompile every new seq length" bug costs 20-40 s of XLA wait per
novel shape in the middle of serving traffic, and nothing in a unit test
notices: every individual call is correct.  Each scenario here declares
the CLOSED ladder of jit-visible widths its entry point may produce and a
compile-key budget; the checker sweeps a request-length ladder through the
real width policy, traces the real jitted function at every distinct
width, hashes (jaxpr, abstract signature, static args) per call — the
compile cache's own key, backend aside — and fails when the keys outgrow
the declaration.

- GC401: distinct compile keys exceed the scenario's declared bound.
- GC402: the width policy emitted a width off the declared ladder (the
  bucketing function regressed, e.g. someone padded to the raw length).
"""

from __future__ import annotations

from .core import Finding


def check(scenarios=None) -> list[Finding]:
    if scenarios is None:
        from .contracts import recompile_scenarios

        scenarios = recompile_scenarios()
    findings: list[Finding] = []
    for sc in scenarios:
        allowed = set(sc.allowed_widths)
        widths: list[int] = []
        off_ladder: set[int] = set()
        for n in sc.ladder:
            w = sc.width_of(n)
            widths.append(w)
            if w not in allowed:
                off_ladder.add(w)
        for w in sorted(off_ladder):
            findings.append(Finding(
                "GC402", sc.path, 0,
                f"{sc.name}: width policy produced {w}, off the declared "
                f"ladder {sorted(allowed)}"))
        keys: dict[str, int] = {}
        try:
            for w in sorted(set(widths) - off_ladder):
                keys[sc.trace(w)] = w
        except Exception as exc:
            findings.append(Finding(
                "GC401", sc.path, 0,
                f"{sc.name}: trace failed at width "
                f"{w}: {type(exc).__name__}: "
                f"{str(exc).splitlines()[0][:160]}"))
            continue
        if len(keys) > sc.max_keys:
            findings.append(Finding(
                "GC401", sc.path, 0,
                f"{sc.name}: {len(keys)} compile keys over the request "
                f"ladder exceed the declared bucket count {sc.max_keys} "
                f"(widths {sorted(keys.values())})"))
    return findings


def measure_keys(scenario) -> dict[str, int]:
    """Compile keys a scenario produces (bench.py compile-stability row):
    key-hash -> width.  Raises on trace failure — the bench row should
    error loudly, not stamp garbage."""
    out: dict[str, int] = {}
    for n in scenario.ladder:
        w = scenario.width_of(n)
        out[scenario.trace(w)] = w
    return out
