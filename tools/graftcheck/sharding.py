"""GC2 — sharding-spec audit over fake meshes.

``parallel/specs.py:param_specs`` is the single source of placement truth;
drift between it and the real param trees is the Megatron-class production
failure (a preset whose tree grew a leaf the specs don't know, an axis name
that no longer exists on the mesh, a dim that stopped dividing).  The audit
structure-matches the spec pytree against ``jax.eval_shape``'d param trees
for every preset x mesh in the ladder — zero FLOPs even for the 70B
presets — and jaxpr-traces the collective ops to verify their axis names
exist on the mesh they run under.

- GC201: spec pytree structure != param tree structure.
- GC202: a PartitionSpec names an axis the mesh does not have.
- GC203: spec rank exceeds the array rank it applies to.
- GC204: an axis shards a dim it does not divide.
- GC205: a traced collective targets an axis missing from the mesh.
"""

from __future__ import annotations

import jax
import jax.tree_util as jtu

from .core import Finding, collect_collectives


def _keystr(kp) -> str:
    return jtu.keystr(kp)


def check_specs(audits=None) -> list[Finding]:
    if audits is None:
        from .contracts import spec_audits

        audits = spec_audits()
    findings: list[Finding] = []
    for audit in audits:
        try:
            tree, specs, mesh = audit.build()
        except Exception as exc:
            findings.append(Finding(
                "GC201", audit.path, 0,
                f"{audit.name}: audit failed to build: "
                f"{type(exc).__name__}: {str(exc).splitlines()[0][:160]}"))
            continue
        if jtu.tree_structure(tree) != jtu.tree_structure(specs):
            tree_keys = {_keystr(k) for k, _ in
                         jtu.tree_flatten_with_path(tree)[0]}
            spec_keys = {_keystr(k) for k, _ in
                         jtu.tree_flatten_with_path(specs)[0]}
            only_params = sorted(tree_keys - spec_keys)[:4]
            only_specs = sorted(spec_keys - tree_keys)[:4]
            findings.append(Finding(
                "GC201", audit.path, 0,
                f"{audit.name}: spec tree drifted from the param tree "
                f"(params-only: {only_params}, specs-only: {only_specs})"))
            continue
        mesh_shape = dict(mesh.shape)
        for (kp, leaf), (_, spec) in zip(
                jtu.tree_flatten_with_path(tree)[0],
                jtu.tree_flatten_with_path(specs)[0]):
            key = _keystr(kp)
            if len(spec) > len(leaf.shape):
                findings.append(Finding(
                    "GC203", audit.path, 0,
                    f"{audit.name}: {key}: spec rank {len(spec)} exceeds "
                    f"array rank {len(leaf.shape)}"))
                continue
            for dim, entry in enumerate(spec):
                if entry is None:
                    continue
                axes = entry if isinstance(entry, tuple) else (entry,)
                for axis in axes:
                    if axis not in mesh_shape:
                        findings.append(Finding(
                            "GC202", audit.path, 0,
                            f"{audit.name}: {key}: unknown mesh axis "
                            f"{axis!r}"))
                        continue
                    size = mesh_shape[axis]
                    if size > 1 and leaf.shape[dim] % size != 0:
                        findings.append(Finding(
                            "GC204", audit.path, 0,
                            f"{audit.name}: {key}: axis {axis!r} (size "
                            f"{size}) shards non-divisible dim {dim} "
                            f"(size {leaf.shape[dim]})"))
    return findings


def check_collectives(audits=None) -> list[Finding]:
    if audits is None:
        from .contracts import collective_audits

        audits = collective_audits()
    findings: list[Finding] = []
    for audit in audits:
        try:
            fn, args, mesh = audit.build()
            jaxpr = jax.make_jaxpr(fn)(*args)
        except Exception as exc:
            findings.append(Finding(
                "GC205", audit.path, 0,
                f"{audit.name}: collective audit failed to trace: "
                f"{type(exc).__name__}: {str(exc).splitlines()[0][:160]}"))
            continue
        mesh_axes = set(mesh.axis_names)
        prims = collect_collectives(jaxpr)
        if not prims:
            findings.append(Finding(
                "GC205", audit.path, 0,
                f"{audit.name}: no collectives in the traced jaxpr — the "
                "audit is vacuous (op rewritten without collectives, or "
                "traced outside shard_map)"))
        for prim, axes in prims.items():
            for axis in axes:
                if axis not in mesh_axes:
                    findings.append(Finding(
                        "GC205", audit.path, 0,
                        f"{audit.name}: {prim} targets axis {axis!r} "
                        f"missing from the mesh {sorted(mesh_axes)}"))
    return findings


def check(audits=None, collectives=None) -> list[Finding]:
    return check_specs(audits) + check_collectives(collectives)
