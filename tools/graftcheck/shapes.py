"""GC1 — shape/dtype contracts via ``jax.eval_shape``.

Every public op and the model forward are traced under abstract values
across a symbolic (batch, seq, heads, pages) sweep — edge sizes included
(1, non-power-of-two, page-boundary) — and every output leaf must land on
its DECLARED shape and dtype.  Because the trace runs the real code, a
failure here is a real TPU bug: a kernel whose output silently changed
dtype, a forward whose cache widened, a GQA ratio that stopped composing.

- GC101: an output leaf's shape or dtype departs from the contract.
- GC102: the contract case fails to trace at all (the op rejects shapes it
  declares it supports).
"""

from __future__ import annotations

import jax

from .core import Finding


def _leaves(out):
    return jax.tree.leaves(
        out, is_leaf=lambda x: hasattr(x, "shape") and hasattr(x, "dtype")
    )


def check(contracts=None) -> list[Finding]:
    if contracts is None:
        from .contracts import op_contracts

        contracts = op_contracts()
    findings: list[Finding] = []
    for contract in contracts:
        try:
            cases = contract.build()
        except Exception as exc:  # registry bug == finding, not crash
            findings.append(Finding(
                "GC102", contract.path, 0,
                f"{contract.name}: contract cases failed to build: "
                f"{type(exc).__name__}: {str(exc).splitlines()[0][:160]}"))
            continue
        for case in cases:
            try:
                out = jax.eval_shape(case.fn, *case.args)
            except Exception as exc:
                findings.append(Finding(
                    "GC102", contract.path, 0,
                    f"{contract.name}[{case.label}]: trace failed: "
                    f"{type(exc).__name__}: "
                    f"{str(exc).splitlines()[0][:160]}"))
                continue
            got = [
                (tuple(leaf.shape), str(leaf.dtype))
                for leaf in _leaves(out)
            ]
            want = [(tuple(s), str(d)) for s, d in case.want]
            if got != want:
                findings.append(Finding(
                    "GC101", contract.path, 0,
                    f"{contract.name}[{case.label}]: output contract "
                    f"violated: declared {want}, traced {got}"))
    return findings
