"""graftcheck core: findings, baseline, jaxpr utilities.

graftcheck is graftlint's semantic sibling: where graftlint reads the AST,
graftcheck EXECUTES the real code under abstract values — ``jax.eval_shape``
over fake meshes, ``jax.make_jaxpr`` over the hot functions, ``.lower()``
over the jitted decode path — so it sees exactly what XLA will see, at zero
FLOPs.  Rule families:

- GC1xx shape/dtype contracts (tools/graftcheck/shapes.py)
- GC2xx sharding-spec audit  (tools/graftcheck/sharding.py)
- GC3xx dtype-promotion lint (tools/graftcheck/dtypes.py)
- GC4xx recompilation hazard (tools/graftcheck/recompile.py)
- GC5xx donation audit       (tools/graftcheck/donation.py)

Findings, suppression-free by design (semantic contracts are fixed or
baselined, never inline-excused), share graftlint's baseline format:
``graftcheck_baseline.txt`` is checked in EMPTY, entries normalize without
line numbers, and ``[xN]`` counts make the baseline a multiset.
"""

from __future__ import annotations

import hashlib
from pathlib import Path

import jax

from tools.graftlint.core import (  # shared format, parametrized by name
    Finding, read_baseline as _read_baseline, split_new,
    write_baseline as _write_baseline,
)

__all__ = [
    "BASELINE_NAME", "Finding", "collect_collectives", "jaxpr_hash",
    "read_baseline", "split_new", "walk_eqns", "write_baseline",
]

BASELINE_NAME = "graftcheck_baseline.txt"

# Collective primitives whose axis names must exist on the mesh they are
# traced under (GC205).  psum2 is what newer lowerings emit for psum.
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmax", "pmin", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "axis_index",
})


def read_baseline(root: Path) -> dict[str, int]:
    return _read_baseline(root, name=BASELINE_NAME)


def write_baseline(root: Path, findings: list[Finding]) -> Path:
    return _write_baseline(
        root, findings, name=BASELINE_NAME, tool="graftcheck"
    )


def walk_eqns(jaxpr):
    """Yield every eqn in a (closed) jaxpr, recursing through call/scan/
    cond/shard_map sub-jaxprs wherever they hide in eqn params."""
    jx = getattr(jaxpr, "jaxpr", jaxpr)
    for eq in jx.eqns:
        yield eq
        for v in eq.params.values():
            for sub in _subjaxprs(v):
                yield from walk_eqns(sub)


def _subjaxprs(v):
    if hasattr(v, "eqns"):  # a raw Jaxpr
        yield v
    elif hasattr(v, "jaxpr"):  # a ClosedJaxpr
        yield v.jaxpr
    elif isinstance(v, (list, tuple)):
        for vv in v:
            yield from _subjaxprs(vv)


def collect_collectives(jaxpr) -> dict[str, set[str]]:
    """primitive name -> set of axis names it targets, over the whole
    jaxpr (sub-jaxprs included)."""
    out: dict[str, set[str]] = {}
    for eq in walk_eqns(jaxpr):
        if eq.primitive.name not in COLLECTIVE_PRIMS:
            continue
        axes = eq.params.get("axis_name", eq.params.get("axes", ()))
        if axes is None:
            axes = ()
        if isinstance(axes, (str, int)):
            axes = (axes,)
        out.setdefault(eq.primitive.name, set()).update(
            str(a) for a in axes
        )
    return out


def jaxpr_hash(fn, *abstract_args, statics: dict | None = None) -> str:
    """Stable hash of the traced program: what the compile cache would key
    on modulo backend — (jaxpr text, abstract input signature, static-arg
    signature).  ``fn`` must close over its static arguments (tracing them
    as inputs would make them unhashable tracers); pass the same values in
    ``statics`` so they contribute to the key verbatim."""
    jaxpr = jax.make_jaxpr(fn)(*abstract_args)
    sig = ",".join(
        f"{tuple(a.shape)}:{a.dtype}" for a in jax.tree.leaves(abstract_args)
    )
    skey = repr(sorted((k, repr(v)) for k, v in (statics or {}).items()))
    return hashlib.sha256(
        (str(jaxpr) + "|" + sig + "|" + skey).encode()
    ).hexdigest()[:16]


def aval_signature(tree) -> str:
    """Compile-key view of a pytree of abstract values."""
    return ",".join(
        f"{tuple(a.shape)}:{a.dtype}" for a in jax.tree.leaves(tree)
    )
