#!/usr/bin/env bash
# Probe the TPU tunnel every INTERVAL seconds; the first time it answers,
# fire tools/tpu_runbook.sh exactly once and exit.  Designed to run in the
# background (nohup tools/tpu_watch.sh & ) while CPU-side work continues.
#
# Usage: tools/tpu_watch.sh [INTERVAL_SECS (default 180)] [PROBE_TIMEOUT (90)]
set -u
cd "$(dirname "$0")/.."
INTERVAL="${1:-180}"
PROBE_TIMEOUT="${2:-90}"
LOG=tools/runbook_out/watch.log
mkdir -p tools/runbook_out

while true; do
  P=$(timeout "$PROBE_TIMEOUT" python -c \
    "import jax; print(jax.devices()[0].platform)" 2>/dev/null | tail -1)
  if [ "$P" = "tpu" ]; then
    echo "[watch $(date -u +%H:%M:%S)] TPU UP — firing runbook" >> "$LOG"
    tools/tpu_runbook.sh >> "$LOG" 2>&1
    echo "[watch $(date -u +%H:%M:%S)] runbook finished (rc=$?)" >> "$LOG"
    exit 0
  fi
  echo "[watch $(date -u +%H:%M:%S)] tunnel down (probe='$P')" >> "$LOG"
  sleep "$INTERVAL"
done
