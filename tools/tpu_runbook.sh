#!/usr/bin/env bash
# TPU evidence runbook (VERDICT r3 task 1).  Run the moment a chip answers:
#
#   1. probe     — jax.devices() in a subprocess with a hard timeout (the axon
#                  plugin blocks ~25 min when the tunnel is down and ignores
#                  JAX_PLATFORMS=cpu, so never probe in-process).
#   2. parity    — tools/kernel_parity.py: both Pallas kernels Mosaic-compiled
#                  on the chip vs references (interpret-mode CI can't catch
#                  lowering failures).
#   3. ladder    — python bench.py --ladder  → BENCH_LADDER.json
#                  (configs 1-4 incl. 3-int8/3-int4/4-int4, flash prefill
#                  rows, serving latency, continuous batching, hbm_util).
#   4. default   — python bench.py           → the north-star 7B-int8 line.
#
# Artifacts land in tools/runbook_out/<UTC timestamp>/ AND BENCH_LADDER.json
# is updated in place (commit it + regenerate BASELINE.md afterwards:
# `python tools/gen_baseline.py`).
#
# Usage: tools/tpu_runbook.sh [--probe-timeout SECS]
set -u -o pipefail  # pipefail: `python ... | tee` must report python's status
cd "$(dirname "$0")/.."

PROBE_TIMEOUT=150
[ "${1:-}" = "--probe-timeout" ] && PROBE_TIMEOUT="$2"

STAMP=$(date -u +%Y%m%dT%H%M%SZ)
OUT="tools/runbook_out/$STAMP"
mkdir -p "$OUT"
log() { echo "[runbook $(date -u +%H:%M:%S)] $*" | tee -a "$OUT/runbook.log"; }

log "probe (timeout ${PROBE_TIMEOUT}s)..."
PLATFORM=$(timeout "$PROBE_TIMEOUT" python -c \
  "import jax; print(jax.devices()[0].platform)" 2>"$OUT/probe.err" | tail -1)
if [ "$PLATFORM" != "tpu" ]; then
  log "probe FAILED (platform='$PLATFORM') — tunnel down or no TPU; aborting."
  exit 2
fi
log "probe OK: tpu"

log "kernel parity (compiled on chip)..."
if timeout 1800 python tools/kernel_parity.py 2>&1 | tee "$OUT/parity.log"; then
  log "parity OK"
else
  log "parity FAILED — ladder still runs (fallback paths measure), but the"
  log "kernel rows are suspect; see $OUT/parity.log"
fi

log "ladder (bench.py --ladder)..."
if timeout 14400 python bench.py --ladder --out BENCH_LADDER.json \
    2>&1 | tee "$OUT/ladder.log"; then
  log "ladder OK"
else
  log "ladder FAILED/TIMED OUT (rc=$?) — BENCH_LADDER.json may be PARTIAL"
  log "(bench.py writes it incrementally); do NOT commit it without checking"
  log "it still carries every config row; see $OUT/ladder.log"
fi
cp -f BENCH_LADDER.json "$OUT/" 2>/dev/null || true

log "default bench (north star)..."
timeout 3600 python bench.py 2>&1 | tee "$OUT/default.log"

log "done — artifacts in $OUT; now: python tools/gen_baseline.py && git add"
log "BENCH_LADDER.json BASELINE.md && git commit"
