#!/usr/bin/env bash
# TPU evidence runbook (VERDICT r3 task 1).  Run the moment a chip answers:
#
#   1. probe     — jax.devices() in a subprocess with a hard timeout (the axon
#                  plugin blocks ~25 min when the tunnel is down and ignores
#                  JAX_PLATFORMS=cpu, so never probe in-process).
#   2. parity    — tools/kernel_parity.py: both Pallas kernels Mosaic-compiled
#                  on the chip vs references (interpret-mode CI can't catch
#                  lowering failures).  Skipped if PARITY_TPU.log already
#                  records a compiled pass (re-run by deleting that file).
#   3. ladder    — ONE ROW PER SUBPROCESS via `bench.py --ladder --rows X`
#                  (merge semantics), each under a hard timeout with a tunnel
#                  probe + retries between rows.  The 2026-07-31 run proved
#                  the tunnel can die minutes after answering: a monolithic
#                  `bench.py --ladder` then wedges in its first device call
#                  and burns the whole availability window; per-row isolation
#                  caps the loss at one row's timeout and keeps every row
#                  that DID land (incremental merge writes).
#   4. default   — python bench.py           → the north-star 7B-int8 line.
#
# Artifacts land in tools/runbook_out/<UTC timestamp>/ AND BENCH_LADDER.json
# is updated in place (commit it + regenerate BASELINE.md afterwards:
# `python tools/gen_baseline.py`).
#
# Usage: tools/tpu_runbook.sh [--probe-timeout SECS]
set -u -o pipefail  # pipefail: `python ... | tee` must report python's status
cd "$(dirname "$0")/.."

PROBE_TIMEOUT=150
[ "${1:-}" = "--probe-timeout" ] && PROBE_TIMEOUT="$2"

STAMP=$(date -u +%Y%m%dT%H%M%SZ)
OUT="tools/runbook_out/$STAMP"
mkdir -p "$OUT"
log() { echo "[runbook $(date -u +%H:%M:%S)] $*" | tee -a "$OUT/runbook.log"; }

# Overall deadline: the per-row loop must never outlive the availability
# window by retrying forever (worst-case unbounded retries would run ~30h).
DEADLINE=$(( $(date +%s) + ${RUNBOOK_MAX_SECS:-21600} ))  # default 6h
# Circuit breaker: consecutive failed probes (tunnel dead) before aborting
# the remaining rows — the watcher can re-fire the runbook on recovery, and
# the merge semantics keep every row that landed.
PROBE_FAILS=0

probe() {  # -> "tpu" on a live tunnel; anything else means down/wedged.
  # stderr lands in $OUT/probe.err (append: per-row probes share it) so a
  # failure distinguishes tunnel-down vs plugin/import errors.
  timeout "$PROBE_TIMEOUT" python -c \
    "import jax; print(jax.devices()[0].platform)" 2>>"$OUT/probe.err" | tail -1
}

log "probe (timeout ${PROBE_TIMEOUT}s)..."
PLATFORM="$(probe)"
if [ "$PLATFORM" != "tpu" ]; then
  log "probe FAILED (platform='$PLATFORM') — tunnel down or no TPU; see"
  log "$OUT/probe.err; aborting."
  exit 2
fi
log "probe OK: tpu"

if grep -q "ALL PASS v3 (compiled" PARITY_TPU.log 2>/dev/null; then
  log "kernel parity: already recorded in PARITY_TPU.log — skipping"
else
  log "kernel parity (compiled on chip)..."
  if timeout 1800 python tools/kernel_parity.py 2>&1 | tee "$OUT/parity.log"; then
    log "parity OK"
  else
    log "parity FAILED — ladder still runs (fallback paths measure), but the"
    log "kernel rows are suspect; see $OUT/parity.log"
  fi
fi

# Row order: north-star configs first so a dying tunnel still yields the
# judged numbers; microbenches and flash rows last.
ROWS_LONG="3-int8 3 3-int4 3-int8-b8 3-int8-b16 4-int4 4-int8 4 \
spec-decode-7b-int8"
ROWS_SHORT="1 1-b32 2 2-b32 serving-latency continuous-batching paged-batching \
chunked-prefill ragged-decode-8k ragged-decode-win-8k quant-matmul-bw \
spec-decode spec-batching prefill-flash-2048 prefill-flash-8192 \
prefill-flash-win-8192 hop-latency"

run_row() {  # run_row <name> <timeout-secs>; rc 0 = row recorded, 3 = abort
  local r="$1" tmo="$2" attempt p rc
  for attempt in 1 2 3; do
    # Wait (bounded by deadline + circuit breaker) for a live tunnel WITHOUT
    # consuming a bench attempt — a few-minute blip must not permanently
    # skip a north-star row while lesser rows then measure for hours.
    while true; do
      if [ "$(date +%s)" -ge "$DEADLINE" ]; then
        log "row $r: RUNBOOK DEADLINE reached — aborting remaining rows"
        return 3
      fi
      p="$(probe)"
      if [ "$p" = "tpu" ]; then
        PROBE_FAILS=0
        break
      fi
      PROBE_FAILS=$((PROBE_FAILS + 1))
      if [ "$PROBE_FAILS" -ge 5 ]; then
        log "row $r: tunnel dead ($PROBE_FAILS consecutive failed probes)" \
            "— circuit open, aborting remaining rows (watcher can re-fire)"
        return 3
      fi
      log "row $r: tunnel down (platform='$p'); waiting 150s" \
          "(probe fail $PROBE_FAILS/5)"
      sleep 150
    done
    timeout "$tmo" python bench.py --ladder --rows "$r" \
        --out BENCH_LADDER.json 2>&1 | tee -a "$OUT/ladder.log"
    rc=$?  # pipefail: python/timeout's status, not tee's (nor a reset 0)
    if [ "$rc" -eq 0 ]; then
      log "row $r: OK"
      return 0
    fi
    log "row $r: failed/timed out (attempt $attempt, rc=$rc, timeout ${tmo}s)"
  done
  log "row $r: GIVING UP after 3 attempts (artifact keeps its prior state)"
  return 1
}

log "ladder (per-row, merged into BENCH_LADDER.json; deadline $(date -u -d "@$DEADLINE" +%H:%M:%S 2>/dev/null || echo +6h))..."
ABORT=0
for r in $ROWS_LONG;  do run_row "$r" 2700; [ $? -eq 3 ] && { ABORT=1; break; }; done
if [ "$ABORT" -eq 0 ]; then
  for r in $ROWS_SHORT; do run_row "$r" 1500; [ $? -eq 3 ] && { ABORT=1; break; }; done
fi
cp -f BENCH_LADDER.json "$OUT/" 2>/dev/null || true
if [ "$ABORT" -eq 1 ]; then
  log "ladder aborted early (deadline/circuit); skipping default bench —"
  log "BENCH_LADDER.json keeps every row that landed"
  exit 3
fi

log "default bench (north star)..."
timeout 3600 python bench.py 2>&1 | tee "$OUT/default.log"

log "done — artifacts in $OUT; now: python tools/gen_baseline.py && git add"
log "BENCH_LADDER.json BASELINE.md && git commit"
