#!/usr/bin/env python
"""AOT compile-only smoke for BASELINE config 5: Llama-3-70B serving shapes
on a 16-device pp x tp mesh (VERDICT r3 next-step 9 contingency).

No hardware (and no 280 GB of weights) needed: params/cache are abstract
``ShapeDtypeStruct``s carrying the real NamedShardings, and
``jax.jit(...).lower(...).compile()`` runs the full GSPMD partitioner +
XLA pipeline — proving the 70B shardings compose (pipeline shard_map,
GQA TP guards, int8-resident quantized leaves) and letting us check the
per-device weight-memory math, without allocating a single parameter.

Run standalone (spawns nothing): ``python tools/aot_70b_smoke.py [n_dev]``.
The test suite drives it via subprocess (tests/parallel/test_aot_70b.py)
because the fake-device count must be set before JAX backend init.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEV = int(sys.argv[1]) if len(sys.argv) > 1 else 16

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={N_DEV}"
)
import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_llms_tpu.checkpoint import quantize as quant_lib
from distributed_llms_tpu.core.config import MeshConfig
from distributed_llms_tpu.models import model as model_lib
from distributed_llms_tpu.models.presets import get_preset
from distributed_llms_tpu.parallel import api as api_lib, pipeline as pipeline_lib
from distributed_llms_tpu.parallel.api import make_parallel_model

HBM_PER_CHIP = 16e9  # v5e


def abstract_sharded(tree, specs, mesh):
    """ShapeDtypeStructs carrying the placement NamedShardings — the same
    path-keyed spec lookup as api._place_tree, minus the device_put."""
    is_q = lambda x: isinstance(x, quant_lib.QuantizedTensor)  # noqa: E731
    spec_by_path = {
        jax.tree_util.keystr(kp): s
        for kp, s in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )[0]
    }

    def place(kp, leaf):
        spec = spec_by_path[jax.tree_util.keystr(kp)]
        if is_q(leaf):
            # Mirror _place_quantized's happy path: data and scale take the
            # weight's spec (shard-divisibility holds for the 70B dims).
            s = tuple(spec) + (None,) * (leaf.data.ndim - len(tuple(spec)))
            return quant_lib.QuantizedTensor(
                data=jax.ShapeDtypeStruct(
                    leaf.data.shape, leaf.data.dtype,
                    sharding=NamedSharding(mesh, P(*s)),
                ),
                scale=jax.ShapeDtypeStruct(
                    leaf.scale.shape, leaf.scale.dtype,
                    sharding=NamedSharding(mesh, P(*s)),
                ),
                bits=leaf.bits, orig_shape=leaf.orig_shape,
                pack_axis=leaf.pack_axis,
            )
        return jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=NamedSharding(mesh, spec)
        )

    return jax.tree_util.tree_map_with_path(place, tree, is_leaf=is_q)


def leaf_bytes_per_device(tree, mesh) -> float:
    """Analytic per-device bytes of a sharded abstract tree."""
    total = 0.0
    for leaf in jax.tree.leaves(tree):
        shards = 1
        spec = getattr(leaf.sharding, "spec", None)
        if spec is not None:
            for ax in spec:
                if ax is None:
                    continue
                for name in (ax if isinstance(ax, tuple) else (ax,)):
                    shards *= mesh.shape.get(name, 1)
        total += leaf.size * leaf.dtype.itemsize / shards
    return total


def main() -> int:
    t0 = time.perf_counter()
    assert jax.default_backend() == "cpu", "refusing to smoke-compile on HW"
    # f32 elementwise math on the fake-CPU mesh (the dryrun's bf16
    # AllReducePromotion crash is a CPU-only XLA pass issue); weights are
    # int8-resident so the per-device memory math is the serving one.
    cfg = get_preset("llama-3-70b", dtype="float32")
    pipe, tp = 4, N_DEV // 4
    mesh_cfg = MeshConfig(pipe=pipe, model=tp)
    pm = make_parallel_model(cfg, mesh_cfg, num_microbatches=4)
    mesh = pm.mesh
    print(f"mesh: pipe={pipe} x model={tp} ({N_DEV} fake devices)")

    # Abstract int8-resident staged params: eval_shape runs init + quantize +
    # staging symbolically — zero bytes allocated.
    def init_staged(key):
        p = model_lib.init_params(key, cfg)
        p["blocks"] = quant_lib.quantize_tree(p["blocks"], bits=8)
        p["blocks"] = pipeline_lib.split_stages(p["blocks"], pipe)
        return p

    abs_params = jax.eval_shape(init_staged, jax.random.key(0))
    specs = api_lib.staged_param_specs(cfg, mesh)
    abs_params = abstract_sharded(abs_params, specs, mesh)
    w_bytes = leaf_bytes_per_device(abs_params, mesh)
    print(f"per-device weight bytes: {w_bytes / 1e9:.2f} GB "
          f"(budget {HBM_PER_CHIP / 1e9:.0f} GB)")
    assert w_bytes < HBM_PER_CHIP, "70B int8 weights do not fit the mesh"

    # Abstract KV cache with the pipeline placement (batch 4, 2048 slots).
    b, s = 4, 2048
    kvh, hd, l = cfg.num_kv_heads, cfg.head_dim_, cfg.num_layers
    kv_ax = "model" if kvh % tp == 0 else None
    cache_spec = P("pipe", None, None, None, kv_ax, None)
    cache_leaf = jax.ShapeDtypeStruct(
        (pipe, l // pipe, b, s, kvh, hd), jnp.dtype(cfg.dtype),
        sharding=NamedSharding(mesh, cache_spec),
    )
    abs_cache = model_lib.KVCache(k=cache_leaf, v=cache_leaf)
    kv_bytes = leaf_bytes_per_device(abs_cache, mesh)
    print(f"per-device KV bytes (b={b}, s={s}): {kv_bytes / 1e9:.2f} GB")
    assert w_bytes + kv_bytes < HBM_PER_CHIP, "weights + KV exceed HBM"

    # 1) Prefill step (T=128 chunk) through the pipeline forward.
    def prefill(params, tokens, cache):
        return pm.forward(params, tokens, cache=cache,
                          cache_index=jnp.int32(0))

    toks = jax.ShapeDtypeStruct((b, 128), jnp.int32,
                                sharding=NamedSharding(mesh, P()))
    lowered = jax.jit(prefill).lower(abs_params, toks, abs_cache)
    compiled = lowered.compile()
    print(f"prefill compile OK [{time.perf_counter() - t0:.1f}s]")
    mem = compiled.memory_analysis()
    if mem is not None:
        print(f"  xla memory analysis: args "
              f"{getattr(mem, 'argument_size_in_bytes', 0) / 1e9:.2f} GB, "
              f"temps {getattr(mem, 'temp_size_in_bytes', 0) / 1e9:.2f} GB")

    # 2) One decode step (T=1, mid-cache write).
    def decode(params, tokens, cache):
        return pm.forward(params, tokens, cache=cache,
                          cache_index=jnp.int32(128))

    tok1 = jax.ShapeDtypeStruct((b, 1), jnp.int32,
                                sharding=NamedSharding(mesh, P()))
    jax.jit(decode).lower(abs_params, tok1, abs_cache).compile()
    print(f"decode compile OK [{time.perf_counter() - t0:.1f}s]")

    print(f"AOT_70B_SMOKE OK: llama-3-70b int8-resident pp{pipe} x tp{tp}, "
          f"{w_bytes / 1e9:.2f} GB weights + {kv_bytes / 1e9:.2f} GB KV "
          f"per chip [{time.perf_counter() - t0:.1f}s]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
