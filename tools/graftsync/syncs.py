"""GS2xx — undeclared host↔device sync points.

The dispatch-ahead engine loop earns its overlap by syncing host↔device
at EXACTLY the declared boundaries: ``_fetch_chunk`` (one batched D2H per
chunk), ``_sync_carry`` (span exit), ``_decode_span``'s automaton
read-back, ``register_prefix``, and the engine's ``_to_host``.  A future
PR that drops a stray ``jax.device_get`` into a helper adds a silent
per-call host round-trip the whole overlap plane then pays for — the
exact regression class the ``decode-overlap`` bench row exists to
surface, caught here before it ships.

**GS201**: a ``jax.device_get(...)`` / ``jax.block_until_ready(...)`` /
``<arr>.block_until_ready()`` call in ``runtime/`` whose enclosing
function is not declared in the ``HOST_SYNC_SITES`` registry
(``runtime/scheduler.py``).  Declaring a new site is one registry line —
the point is that adding a sync is a REVIEWED decision, not an accident.

Module-level sync calls (outside any function) are attributed to the
pseudo-function ``<module>`` and always flagged: import-time device work
is never a sanctioned sync point.
"""

from __future__ import annotations

import ast

from .core import (Finding, FnKey, Project, collect_functions, dotted_name,
                   in_sync_sites, load_registries, scope_files, suppressed)

RULE_SYNC = "GS201"

_SYNC_DOTTED = frozenset({"jax.device_get", "jax.block_until_ready"})
_SYNC_METHODS = frozenset({"block_until_ready"})


def _sync_name(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name in _SYNC_DOTTED:
        return name
    if isinstance(call.func, ast.Attribute) \
            and call.func.attr in _SYNC_METHODS:
        return f"<..>.{call.func.attr}"
    return None


def check(project: Project) -> list[Finding]:
    files = scope_files(project)
    fns = collect_functions(files)
    _, _, sync_sites, _ = load_registries(project)
    findings: list[Finding] = []
    for sf in files:
        owner_of: dict[int, FnKey] = {}
        for key, info in fns.items():
            if info.sf is not sf:
                continue
            for sub in ast.walk(info.node):
                owner_of[id(sub)] = key
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            what = _sync_name(node)
            if what is None:
                continue
            key = owner_of.get(id(node))
            if key is not None and in_sync_sites(key, sync_sites):
                continue
            if suppressed(sf, RULE_SYNC, node.lineno):
                continue
            where = key.pretty() if key is not None else "<module>"
            findings.append(Finding(
                RULE_SYNC, sf.rel, node.lineno,
                f"host<->device sync '{what}' in {where} is not a "
                f"declared sync site — every device_get/block_until_ready "
                f"the engine pays must be a reviewed HOST_SYNC_SITES "
                f"entry (runtime/scheduler.py), or the overlap plane "
                f"silently grows a per-call round-trip",
            ))
    return sorted(findings, key=lambda f: (f.path, f.line))
