"""graftsync core: the lockstep-determinism taint scope — declared
decision surfaces, declared host-sync sites, nondeterminism sources, and
the interprocedural decision closure the GS rule families share.

Every multi-process mesh feature in this engine (overlap dispatch-ahead,
SPMD paged serving, the scheduler hooks) rests on ONE invariant: host-side
scheduling decisions are **byte-identical across lockstep processes**, or
SPMD dispatch deadlocks/diverges.  Until now that invariant lived in
prose ("no wall clocks — mesh lockstep safe").  graftsync machine-checks
it:

- **sources** are nondeterminism: wall clocks (``time.time`` /
  ``perf_counter`` / ``monotonic``), ``random`` / ``np.random`` /
  ``os.urandom`` / ``uuid`` / ``secrets``, ``id()`` / ``hash()`` of
  objects (PYTHONHASHSEED- and allocator-dependent), environment reads,
  and thread/future completion order (``as_completed``);
- **sinks** are the decision surfaces declared in the
  ``LOCKSTEP_DECISIONS`` registry (``runtime/scheduler.py``,
  LOCK_ORDER-style ``"Owner.name" -> doc``): the scheduler hooks plus the
  batcher's span planner / overlap gate / deadline shed;
- taint propagates interprocedurally over graftflow's call-graph
  resolution (a source anywhere in a sink's transitive callees taints the
  decision).

Host↔device sync points get the same registry treatment
(``HOST_SYNC_SITES``): every ``jax.device_get`` / ``block_until_ready``
in ``runtime/`` must sit in a declared site function, so a future PR
cannot silently add a per-chunk sync the overlap loop pays for.  Clock
reads inside a declared sync site are exempt from GS1 — the lockstep
policy is *clock reads only at declared sync points*; metrics/timer
plumbing is exempt via the :data:`METRICS_BOUNDARY` allowlist, never via
suppressions.

Suppressions (both REQUIRE a non-empty reason or they are inert,
graftlint's escape semantics):

- ``# graftsync: lockstep-ok(<reason>)`` on the finding line suppresses
  any GS rule there;
- ``# graftsync: ignore[GS101](<reason>)`` suppresses only the named
  rule(s).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass

from tools.graftlint.core import (Finding, Project, SourceFile,  # noqa: F401
                                  dotted_name, load_project, read_baseline,
                                  split_new, stale_entries, write_baseline)
from tools.graftflow.core import (FnInfo, FnKey,  # noqa: F401
                                  collect_functions, literal_strdict,
                                  local_aliases, resolve_call)

BASELINE_NAME = "graftsync_baseline.txt"

_SUPPRESS_RE = re.compile(
    r"#\s*graftsync:\s*"
    r"(?:(lockstep-ok)|ignore\[([A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*)\])"
    r"\(([^)]*)\)"
)


def suppressed(sf: SourceFile, rule: str, line: int) -> bool:
    """Whether ``rule`` is suppressed on ``line`` (trailing comment, or a
    standalone comment directly above).  A suppression with an EMPTY
    reason is deliberately inert: accepted nondeterminism must say why it
    is lockstep-safe."""
    for m in _SUPPRESS_RE.finditer(sf._comment_for(line)):
        if not m.group(3).strip():
            continue  # reasonless suppressions don't count
        if m.group(1):
            return True
        if rule in re.split(r"\s*,\s*", m.group(2)):
            return True
    return False


# -- scope / registries ------------------------------------------------------

# The lockstep contract binds the ENGINE layer: everything under
# runtime/ (scheduler policy, batcher mechanism, engine entry).  The
# gateway/fleet layer (server, router, cluster/) runs per-process by
# design — its clocks never cross a mesh — but server.py/router.py live
# in runtime/ and their functions are simply never reachable from a
# declared decision, so the closure keeps them out naturally.  Matching
# is by path segment so the self-test fixture trees (pkg/runtime/...)
# land in scope exactly like the real package.
SCOPE_SEGMENT = "runtime/"

# The registry module and the three dict[str, str] literals graftsync
# reads from it (parsed with graftlint's registry parser, so the tools
# can never disagree on what a registry contains).
REGISTRY_MODULE = "runtime/scheduler.py"
DECISIONS_NAME = "LOCKSTEP_DECISIONS"
SYNC_SITES_NAME = "HOST_SYNC_SITES"
HOOKS_NAME = "HOOKS"

# Metrics/logging boundary: calls through these attribute names are
# observability plumbing — their return value is None and nothing they
# compute feeds back into a decision, so (a) taint traversal never
# descends into them and (b) a clock read that only feeds their
# arguments (``METRICS.observe("...", time.perf_counter() - t0)``) is
# exempt BY ALLOWLIST, not by suppression.  This is the "metrics/timer
# reads stay exempt" half of the lockstep clock policy.
METRICS_BOUNDARY = frozenset({
    "inc", "observe", "set_gauge", "set_gauges",
    "info", "debug", "warning", "error", "exception", "log",
})


def scope_files(project: Project) -> list[SourceFile]:
    return [sf for sf in project.package_files() if SCOPE_SEGMENT in sf.rel]


def registry_file(files: list[SourceFile]) -> SourceFile | None:
    return next((f for f in files if f.rel.endswith(REGISTRY_MODULE)), None)


def load_registries(project: Project) -> tuple[
        SourceFile | None, dict[str, str], dict[str, str], dict[str, str]]:
    """-> (registry file, LOCKSTEP_DECISIONS, HOST_SYNC_SITES, HOOKS)."""
    reg = registry_file(scope_files(project))
    if reg is None:
        return None, {}, {}, {}
    return (reg,
            literal_strdict(reg, DECISIONS_NAME) or {},
            literal_strdict(reg, SYNC_SITES_NAME) or {},
            literal_strdict(reg, HOOKS_NAME) or {})


def module_stem(rel: str) -> str:
    return rel.rsplit("/", 1)[-1].removesuffix(".py")


def subclass_closure(files: list[SourceFile]) -> dict[str, set[str]]:
    """class name -> {itself + every (transitive) AST-visible subclass} —
    a registry entry on ``Scheduler.admission_order`` must also bind the
    MixedScheduler/TenantScheduler/SpecMixedScheduler overrides, or a
    subclass override would silently leave the audit."""
    bases: dict[str, set[str]] = {}
    for sf in files:
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                bases[node.name] = {
                    b.id for b in node.bases if isinstance(b, ast.Name)
                }
    out: dict[str, set[str]] = {c: {c} for c in bases}
    changed = True
    while changed:
        changed = False
        for cls, bs in bases.items():
            for b in bs:
                for root, members in out.items():
                    if b in members and cls not in members:
                        members.add(cls)
                        changed = True
    return out


def entry_functions(entry: str, fns: dict[FnKey, FnInfo],
                    subclasses: dict[str, set[str]]) -> list[FnKey]:
    """Functions a registry entry ``"Owner.name"`` binds: the method on
    the named class AND on every subclass that overrides it, or the
    module-level function when ``Owner`` is a module stem."""
    owner, _, name = entry.rpartition(".")
    if not owner:
        return []
    classes = subclasses.get(owner, {owner})
    out = [k for k in fns
           if k.name == name and k.cls is not None and k.cls in classes]
    out += [k for k in fns
            if k.name == name and k.cls is None
            and module_stem(k.rel) == owner]
    return out


def in_sync_sites(key: FnKey, sync_sites: dict[str, str]) -> bool:
    """Whether ``key`` is a declared host-sync site ("Class.method" or
    "module_stem.function")."""
    owner = key.cls if key.cls is not None else module_stem(key.rel)
    return f"{owner}.{key.name}" in sync_sites


# -- nondeterminism sources --------------------------------------------------

_SOURCE_DOTTED = frozenset({
    "time.time", "time.time_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.monotonic", "time.monotonic_ns", "time.process_time",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "os.urandom", "os.getenv", "os.environ.get",
    "uuid.uuid1", "uuid.uuid4",
})
# jax.random is KEYED (deterministic given the carried key) and is the
# sanctioned way to sample — only the stdlib/numpy global-state RNGs are
# nondeterminism.
_SOURCE_PREFIXES = ("random.", "np.random.", "numpy.random.", "secrets.")
_SOURCE_BUILTINS = frozenset({"id", "hash"})
_SOURCE_ATTRS = frozenset({"as_completed"})  # future completion order


def source_name(call: ast.Call) -> str | None:
    """The nondeterminism source a call reads, or None."""
    name = dotted_name(call.func)
    if name in _SOURCE_DOTTED:
        return name
    if name is not None and name.startswith(_SOURCE_PREFIXES):
        return name
    if isinstance(call.func, ast.Name) and call.func.id in _SOURCE_BUILTINS:
        return call.func.id
    if isinstance(call.func, ast.Attribute) \
            and call.func.attr in _SOURCE_ATTRS:
        return f"<..>.{call.func.attr}"
    return None


def env_subscript(node: ast.AST) -> str | None:
    """``os.environ[...]`` reads (a Subscript, not a Call)."""
    if isinstance(node, ast.Subscript) \
            and dotted_name(node.value) == "os.environ":
        return "os.environ[]"
    return None


def metrics_nested_calls(fn: ast.AST) -> set[int]:
    """ids of AST nodes nested inside a METRICS_BOUNDARY call's
    arguments (the allowlisted positions for clock/source reads)."""
    out: set[int] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in METRICS_BOUNDARY):
            for arg in node.args + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    out.add(id(sub))
    return out


# -- decision closure --------------------------------------------------------

@dataclass(frozen=True)
class ClosureEntry:
    entry: FnKey        # the declared decision fn this one is reachable from
    declared: str       # the LOCKSTEP_DECISIONS key that declared it


def _resolve(call: ast.Call, caller: FnKey, aliases: dict[str, str],
             fns: dict[FnKey, FnInfo],
             sched_classes: set[str]) -> list[FnKey]:
    """graftflow's call resolution plus the one edge graftsync needs that
    the collaborator map doesn't carry: ``self.sched.<hook>()`` — the
    batcher's policy field fans out to EVERY scheduler class (the
    concrete policy is chosen at runtime)."""
    out = resolve_call(call, caller, aliases, fns)
    f = call.func
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Attribute)
            and isinstance(f.value.value, ast.Name)
            and f.value.value.id == "self" and f.value.attr == "sched"):
        out += [k for k in fns
                if k.name == f.attr and k.cls in sched_classes]
    return out


def decision_closure(project: Project) -> tuple[
        dict[FnKey, FnInfo], dict[FnKey, ClosureEntry], dict[str, str]]:
    """-> (all scope functions, {reachable fn: its declaring entry},
    LOCKSTEP_DECISIONS).  The closure is every declared decision function
    plus its transitive callees (graftflow's under-approximating call
    resolution: a missed edge can hide a finding, never invent one),
    minus the metrics/logging boundary, which taint never crosses."""
    files = scope_files(project)
    fns = collect_functions(files)
    _, decisions, _, _ = load_registries(project)
    subclasses = subclass_closure(files)
    sched_classes = subclasses.get("Scheduler", set())

    closure: dict[FnKey, ClosureEntry] = {}
    work: list[tuple[FnKey, FnKey, str]] = []
    for declared in decisions:
        for k in entry_functions(declared, fns, subclasses):
            work.append((k, k, declared))
    # Deterministic attribution: sort, then a function is scanned once
    # for the first entry that reached it.
    work.sort(key=lambda t: (t[0].rel, t[0].cls or "", t[0].name, t[2]),
              reverse=True)
    while work:
        key, entry, declared = work.pop()
        if key in closure or key not in fns:
            continue
        closure[key] = ClosureEntry(entry=entry, declared=declared)
        info = fns[key]
        aliases = local_aliases(info.node)
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in METRICS_BOUNDARY):
                continue  # observability boundary: taint never crosses
            for callee in _resolve(node, key, aliases, fns, sched_classes):
                if callee not in closure:
                    work.append((callee, entry, declared))
    return fns, closure, decisions
