"""CLI: ``python -m tools.graftsync [--root DIR] [--only GS1,GS4]``.

Exit status mirrors graftlint/graftcheck/graftflow: 0 when every finding
is absent or baselined, 1 when NEW findings exist, 2 on usage errors.

- ``--only``: comma-separated rule families (GS1..GS4, GSD) — scoped runs
  for fast iteration; the gate and the front door run everything.
- ``--baseline-write``: accept current findings into
  ``graftsync_baseline.txt``.
- ``--write-docs``: regenerate the README "Lockstep determinism" rule
  table.
- ``--all``: also print baselined findings.

Pure AST over ``--root`` (like graftlint/graftflow, unlike graftcheck):
no imports, no tracing — well under a second on this tree (the
``analysis-wall`` bench row stamps the measured number).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftsync",
        description="lockstep-determinism & host-sync audit "
                    "(see tools/graftsync/)",
    )
    ap.add_argument("--root", default=".", help="repo root to analyze")
    ap.add_argument("--only", default=None,
                    help="comma-separated families, e.g. GS1,GS4")
    ap.add_argument("--baseline-write", action="store_true",
                    help="accept current findings into the baseline")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate the README rules table, then exit")
    ap.add_argument("--all", action="store_true",
                    help="also print baselined (accepted) findings")
    args = ap.parse_args(argv)

    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"graftsync: --root {root} is not a directory",
              file=sys.stderr)
        return 2

    from tools.graftsync import (FAMILIES, load_project, read_baseline,
                                 run_project, split_new, write_baseline)

    only = None
    if args.only:
        only = {s.strip() for s in args.only.split(",") if s.strip()}
        unknown = only - set(FAMILIES)
        if unknown:
            print(f"graftsync: unknown families {sorted(unknown)}; "
                  f"have {FAMILIES}", file=sys.stderr)
            return 2

    if args.write_docs:
        from tools.graftsync.docs import write_docs

        done = write_docs(root)
        print("graftsync: rewrote README rules table" if done
              else "graftsync: no rules marker block found")
        return 0

    findings = run_project(load_project(root), only=only)
    if args.baseline_write:
        path = write_baseline(root, findings)
        print(f"graftsync: wrote {len(findings)} finding(s) to {path.name}")
        return 0

    baseline = read_baseline(root)
    new, accepted = split_new(findings, baseline)
    for f in new:
        print(f.render())
    if args.all:
        for f in accepted:
            print(f"{f.render()}  [baselined]")
    from tools.graftlint.core import stale_entries

    stale = stale_entries(findings, baseline)
    print(f"graftsync: {len(new)} new finding(s), {len(accepted)} "
          f"baselined, {len(stale)} stale baseline "
          f"entr{'y' if len(stale) == 1 else 'ies'}", file=sys.stderr)
    for s in stale:
        print(f"  stale: {s}", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
