"""GS3xx — unordered-iteration audit on state feeding decisions.

Python ``set`` iteration order depends on insertion history, hash values,
and (for strings) PYTHONHASHSEED — all of which diverge across lockstep
processes.  A ``for t in some_set`` on a decision path can therefore pick
a different tenant / victim / trigger per process even when every process
holds the SAME set.  Dicts are insertion-ordered, so dict iteration is
deterministic whenever the insertions were (the taint and this audit
together cover that); sets never are.

**GS301**: iteration over a set-typed expression inside the lockstep
decision closure — a ``for`` loop, a list/generator/dict-comprehension
generator, or a ``list()``/``tuple()``/``enumerate()``/``reversed()``
materialization.  Set-typedness is inferred syntactically: set literals,
set comprehensions, ``set()``/``frozenset()`` calls, set-algebra
``|&^-`` of set-typed operands, locals assigned from them, and ``self``
attributes a class (or its bases) assigns a set anywhere.

Deliberately NOT flagged:

- ``sorted(some_set)`` — sorting is the fix; the result is a list;
- SET comprehensions over a set (``{t for t in s}``): the produced value
  is again order-insensitive — only an ORDERED materialization of a set
  is a hazard;
- order-insensitive reductions (``min``/``max``/``sum``/``any``/``all``)
  — ties in ``min``/``max`` keyed selection still break by iteration
  order, so prefer ``sorted`` there too, but flagging every reduction
  would drown the signal.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, decision_closure, suppressed

RULE_ORDER = "GS301"

_SET_ANN = ("set", "Set", "frozenset", "FrozenSet")
_MATERIALIZERS = frozenset({"list", "tuple", "enumerate", "reversed"})
_NEUTRAL = frozenset({"sorted", "min", "max", "sum", "any", "all", "len",
                      "bool", "frozenset", "set"})


def _ann_is_set(ann: ast.expr | None) -> bool:
    if ann is None:
        return False
    base = ann.value if isinstance(ann, ast.Subscript) else ann
    name = base.id if isinstance(base, ast.Name) else \
        base.attr if isinstance(base, ast.Attribute) else ""
    return name in _SET_ANN


def class_set_attrs(files) -> dict[str, set[str]]:
    """class name -> self attributes assigned (or annotated) a set
    anywhere in the class body, closed over AST-visible bases."""
    direct: dict[str, set[str]] = {}
    bases: dict[str, set[str]] = {}
    for sf in files:
        for node in sf.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            bases[node.name] = {
                b.id for b in node.bases if isinstance(b, ast.Name)
            }
            attrs = direct.setdefault(node.name, set())
            for sub in ast.walk(node):
                tgt, val, ann = None, None, None
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt, val = sub.targets[0], sub.value
                elif isinstance(sub, ast.AnnAssign):
                    tgt, val, ann = sub.target, sub.value, sub.annotation
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                if _ann_is_set(ann) or (val is not None
                                        and _expr_is_set(val, set(), set())):
                    attrs.add(tgt.attr)
    # Close over bases (a subclass method iterating a base-class set).
    out: dict[str, set[str]] = {}

    def resolve(cls: str, seen: frozenset = frozenset()) -> set[str]:
        if cls in out:
            return out[cls]
        if cls in seen:
            return direct.get(cls, set())
        got = set(direct.get(cls, set()))
        for b in bases.get(cls, ()):
            got |= resolve(b, seen | {cls})
        out[cls] = got
        return got

    for cls in list(direct):
        resolve(cls)
    return out


def _expr_is_set(expr: ast.expr, local_sets: set[str],
                 attr_sets: set[str]) -> bool:
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) \
            and expr.func.id in ("set", "frozenset"):
        return True
    if isinstance(expr, ast.BinOp) \
            and isinstance(expr.op, (ast.BitOr, ast.BitAnd, ast.BitXor,
                                     ast.Sub)):
        return (_expr_is_set(expr.left, local_sets, attr_sets)
                or _expr_is_set(expr.right, local_sets, attr_sets))
    if isinstance(expr, ast.IfExp):
        return (_expr_is_set(expr.body, local_sets, attr_sets)
                or _expr_is_set(expr.orelse, local_sets, attr_sets))
    if isinstance(expr, ast.Name):
        return expr.id in local_sets
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr in attr_sets
    return False


def _local_sets(fn: ast.AST, attr_sets: set[str]) -> set[str]:
    """Locals assigned a set-typed value (two passes: x = set(); y = x)."""
    out: set[str] = set()
    for _ in range(2):
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and _expr_is_set(node.value, out, attr_sets)):
                out.add(node.targets[0].id)
            elif (isinstance(node, ast.AnnAssign)
                    and isinstance(node.target, ast.Name)
                    and _ann_is_set(node.annotation)):
                out.add(node.target.id)
    return out


def check(project: Project) -> list[Finding]:
    from .core import scope_files

    files = scope_files(project)
    fns, closure, _ = decision_closure(project)
    attr_map = class_set_attrs(files)
    findings: list[Finding] = []
    seen: set[tuple[str, int]] = set()
    for key, ce in closure.items():
        info = fns[key]
        attr_sets = attr_map.get(key.cls or "", set())
        local_sets = _local_sets(info.node, attr_sets)

        def is_set(e: ast.expr) -> bool:
            return _expr_is_set(e, local_sets, attr_sets)

        hits: list[tuple[int, str]] = []
        for node in ast.walk(info.node):
            if isinstance(node, ast.For) and is_set(node.iter):
                hits.append((node.lineno, "for loop"))
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                   ast.DictComp)):
                # A SetComp's product is order-insensitive; these are not.
                for gen in node.generators:
                    if is_set(gen.iter):
                        hits.append((node.lineno, "comprehension"))
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in _MATERIALIZERS
                    and node.args and is_set(node.args[0])):
                hits.append((node.lineno, f"{node.func.id}()"))
        for line, how in hits:
            site = (info.sf.rel, line)
            if site in seen:
                continue
            seen.add(site)
            if suppressed(info.sf, RULE_ORDER, line):
                continue
            via = ("" if key == ce.entry else f" in {key.pretty()}")
            findings.append(Finding(
                RULE_ORDER, info.sf.rel, line,
                f"ordered iteration over an unordered set ({how}){via} "
                f"feeds the lockstep decision "
                f"{ce.entry.pretty()} (LOCKSTEP_DECISIONS "
                f"'{ce.declared}') — set order diverges across processes "
                f"(PYTHONHASHSEED / insertion history); iterate "
                f"sorted(...) or keep the state in an insertion-ordered "
                f"dict/list",
            ))
    return sorted(findings, key=lambda f: (f.path, f.line))
