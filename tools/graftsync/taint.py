"""GS1xx — nondeterminism taint on the lockstep decision path.

A multi-process mesh dispatches SPMD programs in lockstep: every process
must take the SAME admission / victim / bite / sync decision in the same
scheduling round, or the next collective deadlocks (one process dispatches
a program its siblings never will) — the Orca-style continuous-batching
discipline every mesh test in this tree assumes.  A wall-clock read, a
global-state RNG draw, an ``id()``/``hash()``, an env read, or a
future-completion-order dependency anywhere in a decision's CALL GRAPH
breaks that silently: host clocks diverge by construction, CPython hashes
and addresses diverge per process, and the bug only fires as a wedged
mesh in production.

**GS101**: a nondeterminism source (:func:`core.source_name` — wall
clocks, ``random``/``np.random``/``os.urandom``/``uuid``/``secrets``,
``id()``/``hash()``, env reads, ``as_completed``) reachable from a
``LOCKSTEP_DECISIONS`` function over the intra-repo call graph.

The lockstep clock policy's two sanctioned escapes are structural, not
suppressions:

- a source read lexically inside a metrics/logging call's arguments
  (``METRICS.observe("...", time.perf_counter() - t0)``) only feeds
  observability — allowlisted via :data:`core.METRICS_BOUNDARY`;
- a function declared in ``HOST_SYNC_SITES`` IS a sync point — the one
  place timer reads belong (``_fetch_chunk``/``_sync_carry`` stamping
  ``_t_complete``), because the host is already serialized against the
  device there.

Everything else needs ``# graftsync: lockstep-ok(<reason>)`` on the line
— and the reason should say why the value never crosses a process
boundary.
"""

from __future__ import annotations

import ast

from .core import (Finding, Project, decision_closure, env_subscript,
                   in_sync_sites, load_registries, metrics_nested_calls,
                   source_name, suppressed)

RULE_TAINT = "GS101"


def check(project: Project) -> list[Finding]:
    fns, closure, _decisions = decision_closure(project)
    _, _, sync_sites, _ = load_registries(project)
    findings: list[Finding] = []
    seen: set[tuple[str, int]] = set()
    for key, ce in closure.items():
        if in_sync_sites(key, sync_sites):
            continue  # a declared sync point: clock reads belong here
        info = fns[key]
        allowlisted = metrics_nested_calls(info.node)
        for node in ast.walk(info.node):
            what = None
            if isinstance(node, ast.Call):
                what = source_name(node)
            if what is None:
                what = env_subscript(node)
            if what is None:
                continue
            if id(node) in allowlisted:
                continue  # feeds METRICS/log arguments only
            site = (info.sf.rel, node.lineno)
            if site in seen:
                continue
            seen.add(site)
            if suppressed(info.sf, RULE_TAINT, node.lineno):
                continue
            via = ("" if key == ce.entry else f" via {key.pretty()}")
            findings.append(Finding(
                RULE_TAINT, info.sf.rel, node.lineno,
                f"nondeterministic source '{what}' on the lockstep "
                f"decision path: reachable from {ce.entry.pretty()} "
                f"(LOCKSTEP_DECISIONS '{ce.declared}'){via} — processes "
                f"diverge on this value and SPMD dispatch deadlocks; "
                f"read it at a HOST_SYNC_SITES boundary, inject a "
                f"lockstep clock, or derive it from scheduling state",
            ))
    return sorted(findings, key=lambda f: (f.path, f.line))
