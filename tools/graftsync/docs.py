"""GSD01 — README rule-table drift.

The README's "Lockstep determinism" section carries a generated table of
the GS rule families between ``<!-- graftsync:rules:begin/end -->``
markers (the graftlint/graftcheck/graftflow convention): ``python -m
tools.graftsync --write-docs`` regenerates it, and GSD01 fails the gate
when the table diverges from :data:`RULE_DOCS` — the one place each
rule's one-line contract lives.
"""

from __future__ import annotations

import re
from pathlib import Path

from .core import Finding

RULE_DRIFT = "GSD01"

# rule id -> (family, one-line contract).  The README table renders from
# this dict; keep entries in rule order.
RULE_DOCS: dict[str, tuple[str, str]] = {
    "GS101": ("GS1 lockstep taint",
              "no nondeterminism source (wall clock, random/urandom/uuid/"
              "secrets, id()/hash(), env read, future completion order) "
              "reachable from a LOCKSTEP_DECISIONS function over the call "
              "graph; HOST_SYNC_SITES functions and metrics-argument "
              "reads are the two structural exemptions"),
    "GS201": ("GS2 host syncs",
              "every jax.device_get / block_until_ready in runtime/ sits "
              "in a declared HOST_SYNC_SITES function — adding a "
              "host<->device sync is a reviewed registry line, never an "
              "accident the overlap plane silently pays for"),
    "GS301": ("GS3 set ordering",
              "no ordered iteration (for / list-comprehension / list()) "
              "over an unordered set inside the decision closure — set "
              "order diverges across lockstep processes; sorted() and "
              "set-producing comprehensions are clean"),
    "GS401": ("GS4 registry drift",
              "every LOCKSTEP_DECISIONS / HOST_SYNC_SITES entry names a "
              "function something in scope declares"),
    "GS402": ("GS4 registry drift",
              "every scheduler HOOKS entry has a LOCKSTEP_DECISIONS "
              "declaration — a new hook enters the lockstep audit in the "
              "same PR"),
}

_MARKER_RE = re.compile(
    r"<!-- graftsync:rules:begin -->\n(.*?)<!-- graftsync:rules:end -->",
    re.S,
)


def render_table() -> str:
    lines = ["| rule | family | checks |", "| --- | --- | --- |"]
    lines += [f"| {rule} | {fam} | {doc} |"
              for rule, (fam, doc) in RULE_DOCS.items()]
    return "\n".join(lines)


def check_docs(root: Path) -> list[Finding]:
    readme = root / "README.md"
    if not readme.exists():
        return []
    text = readme.read_text(encoding="utf-8")
    m = _MARKER_RE.search(text)
    if m is None:
        return [Finding(
            RULE_DRIFT, "README.md", 1,
            "missing '<!-- graftsync:rules:begin/end -->' block — run "
            "python -m tools.graftsync --write-docs",
        )]
    if m.group(1).strip() != render_table().strip():
        line = text[: m.start()].count("\n") + 1
        return [Finding(
            RULE_DRIFT, "README.md", line,
            "GS rules table is stale vs tools/graftsync/docs.py — run "
            "python -m tools.graftsync --write-docs",
        )]
    return []


def write_docs(root: Path) -> bool:
    readme = root / "README.md"
    if not readme.exists():
        return False
    text = readme.read_text(encoding="utf-8")
    if _MARKER_RE.search(text) is None:
        return False
    block = (f"<!-- graftsync:rules:begin -->\n{render_table()}\n"
             f"<!-- graftsync:rules:end -->")
    # Callable replacement: table text must never be read as re escapes.
    readme.write_text(_MARKER_RE.sub(lambda _m: block, text),
                      encoding="utf-8")
    return True
