"""graftsync — lockstep-determinism & host-sync audit.

The fourth static-analysis tier: graftlint (PR 4) checks statements,
graftcheck (PR 5) traces tensor contracts, graftflow (PR 16) checks
concurrency/resource interactions — graftsync checks the one invariant
every multi-process mesh feature rests on: host-side scheduling decisions
must be byte-identical across lockstep processes.  Taint analysis over
graftflow's call-graph resolution, from nondeterminism sources to the
``LOCKSTEP_DECISIONS`` decision surfaces (tools/graftsync/core.py):

- GS1xx nondeterminism taint          (tools/graftsync/taint.py)
- GS2xx undeclared host<->device sync (tools/graftsync/syncs.py)
- GS3xx unordered-set iteration       (tools/graftsync/ordering.py)
- GS4xx registry drift                (tools/graftsync/drift.py)
- GSD01 README rules-table drift      (tools/graftsync/docs.py)

Run as ``python -m tools.graftsync`` (exit 0 = clean) or through the
unified front door ``python -m tools.check``; the tier-1 pytest gate is
tests/tools/test_graftsync.py::test_repo_is_clean.  Accepted debt lives
in ``graftsync_baseline.txt`` (checked in EMPTY; graftlint's normalized
line-free multiset format).
"""

from __future__ import annotations

from pathlib import Path

from .core import BASELINE_NAME, Finding, Project, load_project, split_new
from tools.graftlint.core import read_baseline as _read_baseline
from tools.graftlint.core import write_baseline as _write_baseline

FAMILIES = ("GS1", "GS2", "GS3", "GS4", "GSD")


def write_baseline(root, findings):
    return _write_baseline(Path(root), findings, name=BASELINE_NAME,
                           tool="graftsync")


def read_baseline(root):
    return _read_baseline(Path(root), name=BASELINE_NAME)


def run_project(project: Project,
                only: set[str] | None = None) -> list[Finding]:
    """Run every rule family (or the ``only`` subset of FAMILIES)."""
    from . import docs, drift, ordering, syncs, taint

    def want(fam: str) -> bool:
        return only is None or fam in only

    findings: list[Finding] = []
    if want("GS1"):
        findings += taint.check(project)
    if want("GS2"):
        findings += syncs.check(project)
    if want("GS3"):
        findings += ordering.check(project)
    if want("GS4"):
        findings += drift.check(project)
    if want("GSD"):
        findings += docs.check_docs(project.root)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.message))


def run(root, only: set[str] | None = None) -> list[Finding]:
    return run_project(load_project(root), only=only)


__all__ = [
    "BASELINE_NAME", "FAMILIES", "Finding", "Project", "load_project",
    "read_baseline", "run", "run_project", "split_new", "write_baseline",
]
