"""GS4xx — registry drift.

The two graftsync registries in ``runtime/scheduler.py`` are only worth
trusting if they cannot rot — the GL305/GF103 lesson applied to lockstep
state:

- **GS401**: a ``LOCKSTEP_DECISIONS`` or ``HOST_SYNC_SITES`` entry names
  a function nothing in scope declares (renamed method, deleted helper)
  — a dead entry reads as audited coverage that no longer exists;
- **GS402**: a scheduler ``HOOKS`` entry with no ``LOCKSTEP_DECISIONS``
  declaration — every hook IS a lockstep decision surface by
  construction (the batcher delegates a scheduling choice through it),
  so a newly added hook must enter the audit in the same PR, not stay
  prose-checked.
"""

from __future__ import annotations

from .core import (Finding, Project, collect_functions, entry_functions,
                   load_registries, scope_files, subclass_closure)

RULE_DEAD = "GS401"
RULE_HOOK = "GS402"


def check(project: Project) -> list[Finding]:
    reg, decisions, sync_sites, hooks = load_registries(project)
    if reg is None:
        return []
    files = scope_files(project)
    fns = collect_functions(files)
    subclasses = subclass_closure(files)
    findings: list[Finding] = []
    for reg_name, registry in ((
            "LOCKSTEP_DECISIONS", decisions), ("HOST_SYNC_SITES",
                                               sync_sites)):
        for entry in sorted(registry):
            if not entry_functions(entry, fns, subclasses):
                findings.append(Finding(
                    RULE_DEAD, reg.rel, 1,
                    f"{reg_name} entry '{entry}' names a function nothing "
                    f"in scope declares — registry drift (rename/delete "
                    f"must update the registry in the same PR)",
                ))
    declared_methods = {e.rpartition(".")[2] for e in decisions}
    for hook in sorted(hooks):
        if hook not in declared_methods:
            findings.append(Finding(
                RULE_HOOK, reg.rel, 1,
                f"scheduler hook '{hook}' (HOOKS) has no "
                f"LOCKSTEP_DECISIONS entry — every hook is a lockstep "
                f"decision surface; declare it so the taint audit "
                f"covers it",
            ))
    return findings
