"""GF3xx — path-sensitive resource pairing.

The KV pool is refcounted by hand, mailboxes are registered by hand, and
semaphores are acquired by hand — and the leak class PRs 2–3 fixed
repeatedly was always the same shape: the pairing held on the happy path
and broke on ONE path (an early return, or an exception thrown between
acquire and release).  GF3 walks every function's CFG — including the
exception edges — and demands the pairing on all of them:

- **GF301** page-pool pairing: pages obtained via ``x = <..>.alloc(...)``
  (or the batcher's ``_alloc_pages`` wrapper), and host-tier swap handles
  obtained via ``x = <..>.park_swap(...)`` (the KV tiering plane — a
  handle nobody stores is host RAM nothing will ever restore or free),
  must be released, stored, returned, or handed to another owner on
  EVERY path from the allocation to function exit, exception exits
  included.  The first statement that mentions ``x`` again counts as the
  sink (conservative: the checker cannot see whether a callee keeps the
  reference), so what this rule pins is the canonical leak — an alloc
  followed by a path (a guard return, a raising call) that forgets the
  pages entirely.  An intervening raising statement needs a
  ``try/finally`` release to be safe.
- **GF302** explicit ``<recv>.acquire()`` (lock/semaphore) must have a
  ``<recv>.release()`` on every path to exit — i.e. in a ``finally`` (or
  the code between them cannot raise or return).  Prefer ``with recv:``.
- **GF303** registry cleanup: a mapping/set field whose ``__init__``
  declaration carries ``# graftflow: cleanup-required`` (the serving
  gateway's ``_requests`` mailbox registry) must not strand entries on
  exception paths: after ``self.f[k] = v`` / ``self.f.add(k)``, every
  path to an EXCEPTION exit must pass a cleanup (``pop``/``del``/
  ``discard``/``remove``/``clear`` on the same field, or a same-class
  helper that performs one).  Normal returns are exempt — outliving the
  function is what a registry is for.
"""

from __future__ import annotations

import ast
import re

from .core import (Finding, FnInfo, Project, build_cfg, collect_functions,
                   exec_parts, expr_text, leaky_paths, mentions_name,
                   scope_files, suppressed)

RULE_PAGES = "GF301"
RULE_ACQUIRE = "GF302"
RULE_REGISTRY = "GF303"

_ALLOC_METHODS = frozenset({"alloc", "_alloc_pages", "park_swap"})
_CLEANUP_METHODS = frozenset({"pop", "discard", "remove", "clear"})
_CLEANUP_RE = re.compile(r"#\s*graftflow:\s*cleanup-required\b")


# -- GF301: page allocations -----------------------------------------------

def _alloc_target(stmt: ast.stmt) -> str | None:
    """Local name bound to an allocation: ``x = <recv>.alloc(n)``."""
    if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr in _ALLOC_METHODS):
        return None
    return stmt.targets[0].id


def _check_pages(info: FnInfo, findings: list[Finding]) -> None:
    cfg = build_cfg(info.node)
    for node in cfg.nodes:
        if node.stmt is None:
            continue
        x = _alloc_target(node.stmt)
        if x is None:
            continue
        line = node.stmt.lineno
        if suppressed(info.sf, RULE_PAGES, line):
            continue

        def clears(n, x=x):
            return mentions_name(n.stmt, x)

        hit = leaky_paths(node, clears, (cfg.exit, cfg.raise_exit))
        if hit is not None:
            how = ("an exception exit" if hit is cfg.raise_exit
                   else "a normal exit")
            findings.append(Finding(
                RULE_PAGES, info.sf.rel, line,
                f"pages allocated into '{x}' in {info.key.pretty()} can "
                f"reach {how} with no release/store on that path — a "
                f"refcount leak the pool audit only catches after the "
                f"fact; release in a finally or store before anything "
                f"can raise",
            ))


# -- GF302: bare acquire/release -------------------------------------------

def _check_acquire(info: FnInfo, findings: list[Finding]) -> None:
    cfg = build_cfg(info.node)
    for node in cfg.nodes:
        stmt = node.stmt
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr == "acquire"):
            continue
        recv = expr_text(stmt.value.func.value)
        line = stmt.lineno
        if suppressed(info.sf, RULE_ACQUIRE, line):
            continue

        def clears(n, recv=recv):
            for part in exec_parts(n.stmt):
                for sub in ast.walk(part):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "release"
                            and expr_text(sub.func.value) == recv):
                        return True
            return False

        hit = leaky_paths(node, clears, (cfg.exit, cfg.raise_exit))
        if hit is not None:
            how = ("an exception exit" if hit is cfg.raise_exit
                   else "a normal exit")
            findings.append(Finding(
                RULE_ACQUIRE, info.sf.rel, line,
                f"'{recv}.acquire()' in {info.key.pretty()} can reach "
                f"{how} without '{recv}.release()' on that path — use "
                f"'with {recv}:' or release in a finally",
            ))


# -- GF303: annotated registry cleanup -------------------------------------

def _annotated_registries(info_sf, cls: ast.ClassDef) -> set[str]:
    """Fields whose declaration carries ``# graftflow: cleanup-required``."""
    out: set[str] = set()
    for node in ast.walk(cls):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target]
                   if isinstance(node, (ast.AnnAssign, ast.AugAssign))
                   else [])
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and _CLEANUP_RE.search(info_sf._comment_for(node.lineno))):
                out.add(t.attr)
    return out


def _is_cleanup(stmt: ast.stmt, field: str) -> bool:
    if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
        # A sweep loop ("for rid in subs: self.f.pop(rid)") is the
        # standard cleanup idiom; the CFG's zero-iteration edge would
        # otherwise read it as skippable.  Trusting the subtree here is a
        # deliberate under-approximation of leaks.
        return _expr_cleans(stmt, field)
    for part in exec_parts(stmt):
        if _expr_cleans(part, field):
            return True
    return False


def _expr_cleans(tree: ast.AST, field: str) -> bool:
    for sub in ast.walk(tree):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _CLEANUP_METHODS
                and isinstance(sub.func.value, ast.Attribute)
                and sub.func.value.attr == field
                and isinstance(sub.func.value.value, ast.Name)
                and sub.func.value.value.id == "self"):
            return True
        if isinstance(sub, ast.Delete):
            for tgt in sub.targets:
                if (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Attribute)
                        and tgt.value.attr == field
                        and isinstance(tgt.value.value, ast.Name)
                        and tgt.value.value.id == "self"):
                    return True
    return False


def _registration(stmt: ast.stmt, field: str) -> bool:
    """``self.f[k] = v`` or ``self.f.add(k)`` (sets)."""
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            if (isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Attribute)
                    and t.value.attr == field
                    and isinstance(t.value.value, ast.Name)
                    and t.value.value.id == "self"):
                return True
    if (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr == "add"
            and isinstance(stmt.value.func.value, ast.Attribute)
            and stmt.value.func.value.attr == field
            and isinstance(stmt.value.func.value.value, ast.Name)
            and stmt.value.func.value.value.id == "self"):
        return True
    return False


def _calls_helper(tree: ast.AST, helpers: set[str]) -> bool:
    for sub in ast.walk(tree):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in helpers
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id == "self"):
            return True
    return False


def _clears_registry(stmt: ast.stmt, field: str, helpers: set[str]) -> bool:
    """Whether this CFG node discharges the registration obligation: a
    cleanup of the field, or a call to a same-class helper that performs
    one (loops get subtree trust — see :func:`_is_cleanup`)."""
    if _is_cleanup(stmt, field):
        return True
    if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
        return _calls_helper(stmt, helpers)
    return any(_calls_helper(part, helpers) for part in exec_parts(stmt))


def _cleanup_helpers(sf, cls: ast.ClassDef, field: str) -> set[str]:
    """Same-class methods that (directly) perform a cleanup of ``field``
    — calling one counts as cleaning up (the interprocedural hop the
    serving handlers actually use)."""
    out: set[str] = set()
    for sub in cls.body:
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_cleanup(s, field) for s in ast.walk(sub)
                   if isinstance(s, ast.stmt)):
                out.add(sub.name)
    return out


def _check_registries(project: Project, findings: list[Finding]) -> None:
    for sf in scope_files(project):
        for cls in sf.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            fields = _annotated_registries(sf, cls)
            if not fields:
                continue
            for field in sorted(fields):
                helpers = _cleanup_helpers(sf, cls, field)
                for fn in cls.body:
                    if not isinstance(fn, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                        continue
                    if fn.name == "__init__":
                        continue  # construction: nothing shared yet
                    cfg = build_cfg(fn)
                    for node in cfg.nodes:
                        if node.stmt is None \
                                or not _registration(node.stmt, field):
                            continue
                        line = node.stmt.lineno
                        if suppressed(sf, RULE_REGISTRY, line):
                            continue

                        def clears(n, field=field, helpers=helpers):
                            return _clears_registry(n.stmt, field, helpers)

                        if leaky_paths(node, clears,
                                       (cfg.raise_exit,)) is not None:
                            findings.append(Finding(
                                RULE_REGISTRY, sf.rel, line,
                                f"an exception path after registering "
                                f"into 'self.{field}' "
                                f"({cls.name}.{fn.name}) strands the "
                                f"entry — the field is marked "
                                f"cleanup-required; pop it in an "
                                f"except/finally on every raising path",
                            ))
    return


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    fns = collect_functions(scope_files(project))
    for info in fns.values():
        _check_pages(info, findings)
        _check_acquire(info, findings)
    _check_registries(project, findings)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
