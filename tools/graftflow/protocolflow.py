"""GF4xx — protocol and drill-plane completeness.

The control plane is string-keyed three times over: frame types
(``MESSAGE_TYPES`` in cluster/protocol.py), NACK/error reasons, and
fault-injection sites (``FAULT_SITES``).  graftlint's GL301/GL305 pin the
*names*; GF4 pins the *flow* — every declared frame must actually move
and be understood, every refusal must be observable, every retry must
terminate, and every drill must sit on a live path:

- **GF401** frame coverage: every ``MESSAGE_TYPES`` entry has at least
  one sender (the type literal built into a frame / passed to a send
  helper) AND at least one handler (the literal compared or matched on a
  receive path) in the package — an unsent type is dead protocol
  surface, an unhandled one is a peer that answers ``invalid message``
  in production only.  A ``message("TYPO")`` literal absent from
  MESSAGE_TYPES is the same finding from the other side.
- **GF402** NACK accounting: a function that sends a structured refusal
  (a frame whose payload carries ``"ok": False``, or an ``ERROR`` frame)
  must increment a metric — refusals that leave no counter trail are
  invisible exactly when the fleet needs them (the PR-7 NACK ladder is
  only debuggable because each reason counts).
- **GF403** bounded retry: a ``while True:`` loop whose except-handler
  catches transport errors (ConnectionError/OSError/Timeout/EOF/
  IncompleteRead/ProtocolError) and ``continue``\\ s, with no
  break/return/raise in that handler, retries forever — every retry site
  must bound its attempts (a counted loop condition, or a guarded exit
  in the handler).
- **GF404** drill liveness: every ``FAULT_SITES`` entry fired in the
  package must have at least one fire site inside a REACHABLE function
  (referenced by name somewhere else in the tree) — a drill wired only
  into dead code passes GL305 yet can never actually fire.
"""

from __future__ import annotations

import ast

from .core import (Finding, Project, collect_functions, literal_strdict,
                   scope_files, suppressed)

RULE_FRAMES = "GF401"
RULE_NACK = "GF402"
RULE_RETRY = "GF403"
RULE_DEAD_FIRE = "GF404"

PROTOCOL_MODULE = "cluster/protocol.py"
FAULTS_MODULE = "runtime/faults.py"

_NETWORK_EXCS = frozenset({
    "ConnectionError", "ConnectionResetError", "BrokenPipeError",
    "OSError", "TimeoutError", "EOFError", "IncompleteReadError",
    "ProtocolError",
})


# graftlint's parser for the ``NAME = frozenset({...})`` literal idiom —
# one definition, so MESSAGE_TYPES reads identically in both tools.
from tools.graftlint.registry import _literal_strset  # noqa: E402


# -- GF401 ------------------------------------------------------------------

def _is_message_call(call: ast.Call) -> bool:
    f = call.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None)
    return name == "message"


def check_frames(project: Project) -> list[Finding]:
    files = scope_files(project)
    proto = next((f for f in files if f.rel.endswith(PROTOCOL_MODULE)), None)
    if proto is None:
        return []
    types = _literal_strset(proto, "MESSAGE_TYPES")
    if not types:
        return [Finding(RULE_FRAMES, proto.rel, 1,
                        "no MESSAGE_TYPES literal declared")]
    senders: dict[str, int] = {t: 0 for t in types}
    handlers: dict[str, int] = {t: 0 for t in types}
    findings: list[Finding] = []
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                lits = [a.value for a in list(node.args)
                        + [kw.value for kw in node.keywords]
                        if isinstance(a, ast.Constant)
                        and isinstance(a.value, str)]
                for v in lits:
                    if v in types:
                        senders[v] += 1
                if _is_message_call(node) and node.args:
                    first = node.args[0]
                    if (isinstance(first, ast.Constant)
                            and isinstance(first.value, str)
                            and first.value not in types
                            and not suppressed(sf, RULE_FRAMES, node.lineno)):
                        findings.append(Finding(
                            RULE_FRAMES, sf.rel, node.lineno,
                            f"frame type {first.value!r} built here is not "
                            f"in MESSAGE_TYPES ({proto.rel}) — "
                            f"protocol.encode will refuse it at runtime",
                        ))
            elif isinstance(node, ast.Compare):
                for side in [node.left] + list(node.comparators):
                    consts = ([side] if isinstance(side, ast.Constant)
                              else [n for n in ast.walk(side)
                                    if isinstance(n, ast.Constant)])
                    for c in consts:
                        if isinstance(c.value, str) and c.value in types:
                            handlers[c.value] += 1
            elif isinstance(node, ast.MatchValue) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str) \
                    and node.value.value in types:
                handlers[node.value.value] += 1
    # The declaration site itself is neither a sender nor a handler; the
    # MESSAGE_TYPES literal lives outside any Call/Compare so it never
    # counted above.  BATCH frames are expanded by unbatch() on receive.
    for t in sorted(types):
        if senders[t] == 0 and not suppressed(proto, RULE_FRAMES, 1):
            findings.append(Finding(
                RULE_FRAMES, proto.rel, 1,
                f"frame type '{t}' has no sender in the package — dead "
                f"protocol surface (or the sender builds its type "
                f"dynamically from an unchecked string)",
            ))
        if handlers[t] == 0 and not suppressed(proto, RULE_FRAMES, 1):
            findings.append(Finding(
                RULE_FRAMES, proto.rel, 1,
                f"frame type '{t}' has no handler in the package — a "
                f"peer sending it gets silence or 'invalid message'",
            ))
    return findings


# -- GF402 ------------------------------------------------------------------

def _sends_nack(call: ast.Call) -> bool:
    """A message(...) construction carrying {"ok": False, ...} or type
    'ERROR'."""
    if not _is_message_call(call) or not call.args:
        return False
    first = call.args[0]
    if isinstance(first, ast.Constant) and first.value == "ERROR":
        return True
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(arg, ast.Dict):
            for k, v in zip(arg.keys, arg.values):
                if (isinstance(k, ast.Constant) and k.value == "ok"
                        and isinstance(v, ast.Constant)
                        and v.value is False):
                    return True
    return False


def _has_metric_inc(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("inc", "observe")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "METRICS"):
            return True
    return False


def check_nacks(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for info in collect_functions(scope_files(project)).values():
        nack_lines = [
            node.lineno for node in ast.walk(info.node)
            if isinstance(node, ast.Call) and _sends_nack(node)
        ]
        if not nack_lines or _has_metric_inc(info.node):
            continue
        line = min(nack_lines)
        if suppressed(info.sf, RULE_NACK, line):
            continue
        findings.append(Finding(
            RULE_NACK, info.sf.rel, line,
            f"{info.key.pretty()} sends a NACK/error frame but increments "
            f"no metric — structured refusals must leave a counter trail "
            f"(register one in METRIC_DOCS and inc it)",
        ))
    return findings


# -- GF403 ------------------------------------------------------------------

def _catches_network(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except swallows transport errors too
    names = {n.attr if isinstance(n, ast.Attribute) else getattr(n, "id", "")
             for n in ([t] if not isinstance(t, ast.Tuple) else t.elts)}
    return bool(names & _NETWORK_EXCS)


def check_retries(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for info in collect_functions(scope_files(project)).values():
        for node in ast.walk(info.node):
            if not (isinstance(node, ast.While)
                    and isinstance(node.test, ast.Constant)
                    and bool(node.test.value)):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.ExceptHandler) \
                        or not _catches_network(sub):
                    continue
                has_continue = any(isinstance(s, ast.Continue)
                                   for s in ast.walk(sub))
                has_exit = any(isinstance(s, (ast.Break, ast.Return,
                                              ast.Raise))
                               for s in ast.walk(sub))
                if has_continue and not has_exit \
                        and not suppressed(info.sf, RULE_RETRY, sub.lineno):
                    findings.append(Finding(
                        RULE_RETRY, info.sf.rel, sub.lineno,
                        f"unbounded retry in {info.key.pretty()}: 'while "
                        f"True' catches a transport error and continues "
                        f"with no break/return/raise in the handler — "
                        f"bound the attempts or make the loop condition "
                        f"count them",
                    ))
    return findings


# -- GF404 ------------------------------------------------------------------

def _referenced_names(project: Project) -> set[str]:
    """Every function/method name referenced anywhere in the tree other
    than as its own def — calls AND bare references (thread targets,
    callbacks, handler registration)."""
    out: set[str] = set()
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Attribute):
                out.add(node.attr)
            elif isinstance(node, ast.Name):
                out.add(node.id)
    return out


def check_fire_liveness(project: Project) -> list[Finding]:
    files = scope_files(project)
    faults = next((f for f in files if f.rel.endswith(FAULTS_MODULE)), None)
    if faults is None:
        return []
    registry = literal_strdict(faults, "FAULT_SITES")
    if not registry:
        return []
    refs = _referenced_names(project)
    fns = collect_functions(files)
    # site -> list of (fn_key, line, reachable)
    sites: dict[str, list[tuple]] = {}
    for info in fns.values():
        for node in ast.walk(info.node):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "fire" and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                site = node.args[0].value
                reachable = (
                    info.key.name in refs
                    or info.key.name.startswith("__")
                    or info.key.name in ("main", "run")
                )
                sites.setdefault(site, []).append(
                    (info, node.lineno, reachable))
        # module-level fire calls (outside any def) are always live; they
        # are not collected here, so sites fired only there stay silent —
        # acceptable: the tree has none.
    findings: list[Finding] = []
    for site, uses in sorted(sites.items()):
        if site not in registry:
            continue  # GL301's finding, not ours
        if any(reachable for _info, _ln, reachable in uses):
            continue
        info, line, _ = uses[0]
        if suppressed(info.sf, RULE_DEAD_FIRE, line):
            continue
        findings.append(Finding(
            RULE_DEAD_FIRE, info.sf.rel, line,
            f"fault site '{site}' is fired only from "
            f"{info.key.pretty()}, which nothing in the tree references "
            f"— the drill is wired into dead code and can never fire",
        ))
    return findings


def check(project: Project) -> list[Finding]:
    return sorted(
        check_frames(project) + check_nacks(project)
        + check_retries(project) + check_fire_liveness(project),
        key=lambda f: (f.path, f.line, f.rule, f.message),
    )
