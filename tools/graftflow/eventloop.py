"""GF2xx — event-loop blocking audit.

One asyncio event loop per process answers /healthz probes, routes
requests, runs the fleet's failure detection, and shuttles KV handoffs.
A single synchronous zlib/pickle/socket/file call anywhere in a
coroutine's CALL GRAPH stalls all of it at once — PR 7 shipped exactly
this bug (multi-MB zlib inside the KV send path wedging the same loop the
fleet probes) and it was found by review, not by a gate.  GF2 is that
gate:

- **GF201**: a blocking call (``time.sleep``, zlib/pickle, sockets,
  subprocess, file I/O, requests/urllib) lexically inside an ``async
  def`` in scope, or inside a SYNC function transitively reachable from
  one over the intra-repo call graph.  Work wrapped in
  ``asyncio.to_thread(fn, ...)`` is off the loop and is never traversed
  (the function is an argument there, not a call).
- **GF202**: a ``FaultPlane.fire(...)`` call reachable from a coroutine
  without ``defer_stall=True``.  ``fire`` applies ``stall`` rules with a
  blocking sleep by design (it models a wedged device call for the
  engine-thread sites); event-loop call sites must ask for the rule back
  and await it instead — a drill armed at such a site would otherwise
  freeze the whole loop, failure detection included.

Findings land on the blocking call's line; deliberate blocks carry
``# graftflow: ok(<reason>)`` there.
"""

from __future__ import annotations

import ast

from .core import (Finding, FnInfo, FnKey, Project, collect_functions,
                   dotted_name, local_aliases, resolve_call, scope_files,
                   suppressed)

RULE_BLOCKING = "GF201"
RULE_FIRE = "GF202"

_BLOCKING_DOTTED = frozenset({
    "time.sleep",
    "zlib.compress", "zlib.decompress",
    "pickle.dumps", "pickle.loads", "pickle.dump", "pickle.load",
    "socket.socket", "socket.create_connection",
    "os.system", "os.popen",
})
_BLOCKING_PREFIXES = ("subprocess.", "requests.", "urllib.request.")
_BLOCKING_METHODS = frozenset({
    "read_text", "write_text", "read_bytes", "write_bytes",
    # zlib (de)compression objects: d.compress/.decompress — the exact
    # PR-7 pattern once the one-liner is split into an object form.
    "compress", "decompress",
})


def _blocking_name(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name in _BLOCKING_DOTTED:
        return name
    if name is not None and name.startswith(_BLOCKING_PREFIXES):
        return name
    if isinstance(call.func, ast.Name) and call.func.id == "open":
        return "open"
    if isinstance(call.func, ast.Attribute) \
            and call.func.attr in _BLOCKING_METHODS:
        return f"<..>.{call.func.attr}"
    return None


def _is_to_thread(call: ast.Call) -> bool:
    return dotted_name(call.func) in ("asyncio.to_thread",
                                      "anyio.to_thread.run_sync")


def _is_fire(call: ast.Call) -> bool:
    """A FaultPlane.fire site: ``.fire('<site>', ...)`` (GL301's shape),
    or ``.fire(<expr>, ...)`` on a receiver that is recognizably a fault
    plane (``self.faults``, ``plane``, ``_FAULTS`` — protocol.py passes
    its site as a variable)."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "fire" and bool(call.args)):
        return False
    if isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return True
    recv = (dotted_name(call.func.value) or "").lower()
    return "fault" in recv or "plane" in recv


def _fire_site(call: ast.Call) -> str:
    first = call.args[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str):
        return repr(first.value)
    return "<dynamic site>"


def _fire_deferred(call: ast.Call) -> bool:
    return any(kw.arg == "defer_stall"
               and isinstance(kw.value, ast.Constant) and kw.value.value is True
               for kw in call.keywords)


def _scan_fn(info: FnInfo, entry: FnKey, fns: dict[FnKey, FnInfo],
             findings: list[Finding], seen_sites: set,
             reach: list[tuple[FnKey, FnKey]]) -> None:
    """Flag blocking calls in one function and queue sync callees.
    ``entry`` is the coroutine this function is reachable from (for the
    message); nested defs are included in the walk (a closure defined in
    a coroutine typically runs on the loop — call_soon, callbacks)."""
    aliases = local_aliases(info.node)
    for node in ast.walk(info.node):
        if not isinstance(node, ast.Call):
            continue
        if _is_to_thread(node):
            continue  # arguments are references, not calls: off-loop
        if _is_fire(node):
            if not _fire_deferred(node):
                site = (info.sf.rel, node.lineno, RULE_FIRE)
                if site not in seen_sites:
                    seen_sites.add(site)
                    if not suppressed(info.sf, RULE_FIRE, node.lineno):
                        findings.append(Finding(
                            RULE_FIRE, info.sf.rel, node.lineno,
                            f"FaultPlane.fire({_fire_site(node)}) without "
                            f"defer_stall=True in {info.key.pretty()} is "
                            f"reachable from the event loop (async "
                            f"{entry.pretty()}) — a stall rule here would "
                            f"block the loop, failure detection included",
                        ))
            continue  # fire's own guarded sleep is the deferral's job
        what = _blocking_name(node)
        if what is not None:
            site = (info.sf.rel, node.lineno, RULE_BLOCKING)
            if site in seen_sites:
                continue
            seen_sites.add(site)
            if not suppressed(info.sf, RULE_BLOCKING, node.lineno):
                via = ("" if info.key == entry
                       else f" via {info.key.pretty()}")
                findings.append(Finding(
                    RULE_BLOCKING, info.sf.rel, node.lineno,
                    f"blocking call '{what}' runs on the event loop: "
                    f"reachable from async {entry.pretty()}{via} — wrap "
                    f"the work in asyncio.to_thread or move it off the "
                    f"coroutine path",
                ))
            continue
        for callee in resolve_call(node, info.key, aliases, fns):
            target = fns.get(callee)
            if target is not None and not target.is_async:
                reach.append((callee, entry))


def check(project: Project) -> list[Finding]:
    files = scope_files(project)
    fns = collect_functions(files)
    findings: list[Finding] = []
    seen_sites: set = set()
    # A function is scanned ONCE, attributed to the first coroutine that
    # reached it (seen_sites additionally dedupes the finding lines).
    done: set[FnKey] = set()
    # Every coroutine in scope is an entry point: handlers, probe loops,
    # transfer paths — anything awaited eventually runs on the loop.
    work: list[tuple[FnKey, FnKey]] = sorted(
        ((k, k) for k, info in fns.items() if info.is_async),
        key=lambda kk: (kk[0].rel, kk[0].cls or "", kk[0].name),
        reverse=True,  # popped in order: deterministic attribution
    )
    while work:
        key, entry = work.pop()
        if key in done:
            continue
        done.add(key)
        _scan_fn(fns[key], entry, fns, findings, seen_sites, work)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
