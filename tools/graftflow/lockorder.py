"""GF1xx — interprocedural lock-order audit.

The serving core holds real ``threading.Lock``\\ s on three layers —
``InferenceServer._submit_lock`` (loop-side submission/registry),
``ContinuousBatcher._lock`` (queue + kv-import handoff), and
``PagePool._lock`` (allocator + prefix-cache LRU), with the process-wide
``Metrics._lock`` as the universal leaf — and the documented acquisition
order (server.py: "lock order is _submit_lock -> batcher._lock,
everywhere") lived only in comments.  A new call path that nests the
other way is a deadlock that no unit test will find (it needs two threads
to interleave exactly wrong).  Linux lockdep mechanizes exactly this
class at runtime; GF1 mechanizes it statically:

- the checker builds the GLOBAL lock-acquisition graph: an edge A -> B
  for every site that acquires B (lexical ``with <lock>:``) while holding
  A (an enclosing ``with``, a ``# graftlint: holds(<lock>)`` annotation,
  or a lock held by a CALLER, propagated over the intra-repo call graph);
- **GF101**: any cycle in that graph (including A -> A: these are
  non-reentrant locks);
- **GF102**: any edge that contradicts the declared ``LOCK_ORDER``
  registry in ``runtime/faults.py`` (outermost first, FAULT_SITES-style
  name -> one-line doc);
- **GF103**: a ``LOCK_ORDER`` entry naming a lock no class in scope
  declares — registry drift, the dead-entry class GL305 pins for fault
  sites.

Lock identity is ``Class.field`` (``with self._lock:`` in PagePool is
``PagePool._lock``; ``with self.pool._lock:`` in the batcher resolves
through the collaborator field map).  Only attributes whose name contains
``lock`` participate — asyncio semaphores and other ``with`` contexts are
not mutual-exclusion order hazards between threads.
"""

from __future__ import annotations

import ast

from .core import (FIELD_CLASSES, Finding, FnInfo, FnKey, GLOBAL_CLASSES,
                   Project, collect_functions, literal_strdict, local_aliases,
                   resolve_call, scope_files, suppressed)

RULE_CYCLE = "GF101"
RULE_ORDER = "GF102"
RULE_DRIFT = "GF103"

REGISTRY_MODULE = "runtime/faults.py"
REGISTRY_NAME = "LOCK_ORDER"


def _lockish(name: str) -> bool:
    return "lock" in name.lower()


def lock_of_expr(expr: ast.expr, cls: str | None,
                 aliases: dict[str, str]) -> str | None:
    """Canonical ``Class.field`` name of a lock expression, or None."""
    if isinstance(expr, ast.Attribute) and _lockish(expr.attr):
        v = expr.value
        if isinstance(v, ast.Name):
            if v.id == "self" and cls is not None:
                return f"{cls}.{expr.attr}"
            if v.id in aliases:
                return f"{aliases[v.id]}.{expr.attr}"
            if v.id in GLOBAL_CLASSES:
                return f"{GLOBAL_CLASSES[v.id]}.{expr.attr}"
        elif (isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name) and v.value.id == "self"
                and v.attr in FIELD_CLASSES):
            return f"{FIELD_CLASSES[v.attr]}.{expr.attr}"
    return None


def _holds_of(info: FnInfo) -> set[str]:
    """holds() annotations translated to canonical lock names."""
    out: set[str] = set()
    for text in info.sf.holds_locks(info.node):
        # normalized "self._lock" / "self.pool._lock" strings
        try:
            expr = ast.parse(text, mode="eval").body
        except SyntaxError:
            continue
        lock = lock_of_expr(expr, info.key.cls, {})
        if lock is not None:
            out.add(lock)
    return out


class _Acquisition:
    __slots__ = ("held", "lock", "rel", "line", "where")

    def __init__(self, held: frozenset, lock: str, rel: str, line: int,
                 where: str) -> None:
        self.held = held
        self.lock = lock
        self.rel = rel
        self.line = line
        self.where = where


class _FnWalk(ast.NodeVisitor):
    """One pass over one function body with a given entry-held set:
    records lock acquisitions (with the locks held at that point) and
    call sites (with the held set to propagate to callees)."""

    def __init__(self, info: FnInfo, entry_held: frozenset,
                 fns: dict[FnKey, FnInfo]) -> None:
        self.info = info
        self.fns = fns
        self.aliases = local_aliases(info.node)
        self.held: list[str] = sorted(entry_held)
        self.acquisitions: list[_Acquisition] = []
        self.calls: list[tuple[FnKey, frozenset]] = []

    def run(self) -> None:
        for stmt in self.info.node.body:
            self.visit(stmt)

    # with-blocks do not cross function boundaries: a nested def runs
    # whenever it is CALLED, not where it is defined.
    def visit_FunctionDef(self, node) -> None:  # noqa: N802
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def _visit_with(self, node) -> None:
        got: list[str] = []
        for item in node.items:
            lock = lock_of_expr(item.context_expr, self.info.key.cls,
                                self.aliases)
            if lock is not None:
                self.acquisitions.append(_Acquisition(
                    frozenset(self.held + got), lock, self.info.sf.rel,
                    node.lineno, self.info.key.pretty(),
                ))
                got.append(lock)
        self.held += got
        self.generic_visit(node)
        if got:
            del self.held[len(self.held) - len(got):]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_Call(self, node: ast.Call) -> None:  # noqa: N802
        for callee in resolve_call(node, self.info.key, self.aliases,
                                   self.fns):
            self.calls.append((callee, frozenset(self.held)))
        self.generic_visit(node)


def build_acquisition_graph(
    fns: dict[FnKey, FnInfo],
) -> list[_Acquisition]:
    """Interprocedural fixpoint: run every function under every distinct
    entry-held set that reaches it (holds() annotations seed; call sites
    propagate)."""
    acquisitions: list[_Acquisition] = []
    done: set[tuple[FnKey, frozenset]] = set()
    work: list[tuple[FnKey, frozenset]] = [
        (k, frozenset(_holds_of(info))) for k, info in fns.items()
    ]
    while work:
        key, entry = work.pop()
        if (key, entry) in done or key not in fns:
            continue
        done.add((key, entry))
        walk = _FnWalk(fns[key], entry | _holds_of(fns[key]), fns)
        walk.run()
        acquisitions.extend(walk.acquisitions)
        for callee, held in walk.calls:
            if held and (callee, held) not in done:
                work.append((callee, held))
    return acquisitions


def _cycle_edges(edges: dict[tuple[str, str], _Acquisition]
                 ) -> list[tuple[str, str]]:
    """Edges that sit on a cycle: (a, b) where b reaches a."""
    adj: dict[str, set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)

    def reaches(src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(adj.get(n, ()))
        return False

    return [(a, b) for (a, b) in edges if reaches(b, a)]


def _declared_locks_exist(project: Project, registry: dict[str, str]
                          ) -> dict[str, bool]:
    """lock name -> whether some class in scope assigns that attribute."""
    assigned: set[str] = set()
    for sf in scope_files(project):
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in ast.walk(node):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target]
                           if isinstance(sub, (ast.AnnAssign, ast.AugAssign))
                           else [])
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        assigned.add(f"{node.name}.{t.attr}")
    return {lock: lock in assigned for lock in registry}


def check(project: Project) -> list[Finding]:
    files = scope_files(project)
    if not files:
        return []
    reg_file = next(
        (f for f in files if f.rel.endswith(REGISTRY_MODULE)), None)
    registry = (literal_strdict(reg_file, REGISTRY_NAME)
                if reg_file is not None else None) or {}
    order = {lock: i for i, lock in enumerate(registry)}

    fns = collect_functions(files)
    acquisitions = build_acquisition_graph(fns)

    # Collapse to one witness per directed edge (first by file/line).
    edges: dict[tuple[str, str], _Acquisition] = {}
    for acq in sorted(acquisitions, key=lambda a: (a.rel, a.line)):
        for held in acq.held:
            edges.setdefault((held, acq.lock), acq)

    findings: list[Finding] = []
    on_cycle = set(_cycle_edges(edges))
    for (a, b), acq in sorted(edges.items()):
        sf = next(f for f in files if f.rel == acq.rel)
        if (a, b) in on_cycle:
            if not suppressed(sf, RULE_CYCLE, acq.line):
                findings.append(Finding(
                    RULE_CYCLE, acq.rel, acq.line,
                    f"lock-order cycle: {acq.where} acquires '{b}' while "
                    f"holding '{a}', and '{b}' is (transitively) held "
                    f"around '{a}' elsewhere — two threads interleaving "
                    f"these paths deadlock",
                ))
            continue
        if a in order and b in order and order[a] > order[b]:
            if not suppressed(sf, RULE_ORDER, acq.line):
                findings.append(Finding(
                    RULE_ORDER, acq.rel, acq.line,
                    f"{acq.where} acquires '{b}' while holding '{a}' — "
                    f"LOCK_ORDER ({REGISTRY_MODULE}) ranks '{b}' before "
                    f"'{a}'; nest the other way or split the critical "
                    f"section",
                ))
    if reg_file is not None and registry:
        for lock, exists in sorted(
                _declared_locks_exist(project, registry).items()):
            if not exists:
                findings.append(Finding(
                    RULE_DRIFT, reg_file.rel, 1,
                    f"LOCK_ORDER entry '{lock}' names a lock no class in "
                    f"scope declares — registry drift",
                ))
    return findings
