"""graftflow — interprocedural concurrency & resource-safety checker.

The third static-analysis tier: graftlint (PR 4) checks statements,
graftcheck (PR 5) traces tensor contracts, graftflow checks the
*interactions* the distributed serving layer lives or dies by — built on
per-function control-flow graphs (with exception edges) and an
intra-repo call graph (tools/graftflow/core.py):

- GF1xx lock-order audit          (tools/graftflow/lockorder.py)
- GF2xx event-loop blocking       (tools/graftflow/eventloop.py)
- GF3xx resource pairing          (tools/graftflow/resources.py)
- GF4xx protocol completeness     (tools/graftflow/protocolflow.py)
- GFD01 README rules-table drift  (tools/graftflow/docs.py)

Run as ``python -m tools.graftflow`` (exit 0 = clean) or through the
unified front door ``python -m tools.check``; the tier-1 pytest gate is
tests/tools/test_graftflow.py::test_repo_is_clean.  Accepted debt lives
in ``graftflow_baseline.txt`` (checked in EMPTY; graftlint's normalized
line-free multiset format).
"""

from __future__ import annotations

from pathlib import Path

from .core import BASELINE_NAME, Finding, Project, load_project, split_new
from tools.graftlint.core import read_baseline as _read_baseline
from tools.graftlint.core import write_baseline as _write_baseline

FAMILIES = ("GF1", "GF2", "GF3", "GF4", "GFD")


def write_baseline(root, findings):
    return _write_baseline(Path(root), findings, name=BASELINE_NAME,
                           tool="graftflow")


def read_baseline(root):
    return _read_baseline(Path(root), name=BASELINE_NAME)


def run_project(project: Project,
                only: set[str] | None = None) -> list[Finding]:
    """Run every rule family (or the ``only`` subset of FAMILIES)."""
    from . import docs, eventloop, lockorder, protocolflow, resources

    def want(fam: str) -> bool:
        return only is None or fam in only

    findings: list[Finding] = []
    if want("GF1"):
        findings += lockorder.check(project)
    if want("GF2"):
        findings += eventloop.check(project)
    if want("GF3"):
        findings += resources.check(project)
    if want("GF4"):
        findings += protocolflow.check(project)
    if want("GFD"):
        findings += docs.check_docs(project.root)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.message))


def run(root, only: set[str] | None = None) -> list[Finding]:
    return run_project(load_project(root), only=only)


__all__ = [
    "BASELINE_NAME", "FAMILIES", "Finding", "Project", "load_project",
    "read_baseline", "run", "run_project", "split_new", "write_baseline",
]
