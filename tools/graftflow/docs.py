"""GFD01 — README rule-table drift.

The README's "Dataflow checks" section carries a generated table of the
GF rule families between ``<!-- graftflow:rules:begin/end -->`` markers
(the graftlint/graftcheck convention): ``python -m tools.graftflow
--write-docs`` regenerates it, and GFD01 fails the gate when the table
diverges from :data:`RULE_DOCS` — the one place each rule's one-line
contract lives.
"""

from __future__ import annotations

import re
from pathlib import Path

from .core import Finding

RULE_DRIFT = "GFD01"

# rule id -> (family, one-line contract).  The README table renders from
# this dict; keep entries in rule order.
RULE_DOCS: dict[str, tuple[str, str]] = {
    "GF101": ("GF1 lock order",
              "no cycle in the global lock-acquisition graph (with-nesting "
              "+ holds() annotations, propagated over the call graph)"),
    "GF102": ("GF1 lock order",
              "every nested acquisition follows the declared LOCK_ORDER "
              "registry (runtime/faults.py, outermost first)"),
    "GF103": ("GF1 lock order",
              "every LOCK_ORDER entry names a lock some class in scope "
              "actually declares"),
    "GF201": ("GF2 event loop",
              "no blocking call (zlib/pickle/socket/file I/O/time.sleep/"
              "subprocess) reachable from a coroutine outside "
              "asyncio.to_thread"),
    "GF202": ("GF2 event loop",
              "every FaultPlane.fire reachable from a coroutine passes "
              "defer_stall=True (a stall rule must never block the loop)"),
    "GF301": ("GF3 resources",
              "allocated KV pages AND host-tier swap handles (park_swap) "
              "reach a release/store/handoff on every CFG path, "
              "exception edges included"),
    "GF302": ("GF3 resources",
              "every bare .acquire() pairs with .release() on all paths "
              "(prefer 'with')"),
    "GF303": ("GF3 resources",
              "cleanup-required registries (# graftflow: cleanup-required) "
              "never strand an entry on an exception path"),
    "GF401": ("GF4 protocol",
              "every MESSAGE_TYPES frame has a sender and a handler; no "
              "frame is built with an undeclared type"),
    "GF402": ("GF4 protocol",
              "every NACK/ERROR frame send increments a metric"),
    "GF403": ("GF4 protocol",
              "no unbounded transport-error retry loop (while True + "
              "except + continue with no bounded exit)"),
    "GF404": ("GF4 protocol",
              "every fault site is fired from code something actually "
              "references (no drills wired into dead functions)"),
}

_MARKER_RE = re.compile(
    r"<!-- graftflow:rules:begin -->\n(.*?)<!-- graftflow:rules:end -->",
    re.S,
)


def render_table() -> str:
    lines = ["| rule | family | checks |", "| --- | --- | --- |"]
    lines += [f"| {rule} | {fam} | {doc} |"
              for rule, (fam, doc) in RULE_DOCS.items()]
    return "\n".join(lines)


def check_docs(root: Path) -> list[Finding]:
    readme = root / "README.md"
    if not readme.exists():
        return []
    text = readme.read_text(encoding="utf-8")
    m = _MARKER_RE.search(text)
    if m is None:
        return [Finding(
            RULE_DRIFT, "README.md", 1,
            "missing '<!-- graftflow:rules:begin/end -->' block — run "
            "python -m tools.graftflow --write-docs",
        )]
    if m.group(1).strip() != render_table().strip():
        line = text[: m.start()].count("\n") + 1
        return [Finding(
            RULE_DRIFT, "README.md", line,
            "GF rules table is stale vs tools/graftflow/docs.py — run "
            "python -m tools.graftflow --write-docs",
        )]
    return []


def write_docs(root: Path) -> bool:
    readme = root / "README.md"
    if not readme.exists():
        return False
    text = readme.read_text(encoding="utf-8")
    if _MARKER_RE.search(text) is None:
        return False
    block = (f"<!-- graftflow:rules:begin -->\n{render_table()}\n"
             f"<!-- graftflow:rules:end -->")
    # Callable replacement: table text must never be read as re escapes.
    readme.write_text(_MARKER_RE.sub(lambda _m: block, text),
                      encoding="utf-8")
    return True
