"""graftflow core: per-function CFGs, an intra-repo call graph, and the
shared plumbing the GF rule families build on.

graftlint (tools/graftlint) reads the AST one statement at a time and
graftcheck (tools/graftcheck) traces the real code under abstract values;
graftflow sits between them: it builds *control-flow graphs* (statement
nodes, normal successors, and EXCEPTION edges from every raising
statement to the innermost handler/finally or out of the function) and an
*interprocedural call graph* (same-module functions, ``self.*`` methods,
known collaborator fields, known module aliases), so it can answer
path-sensitive questions the per-statement rules cannot:

- which locks are held when another lock is acquired, across calls (GF1);
- which blocking calls a coroutine can reach transitively (GF2);
- whether an allocation can reach function exit unreleased along ANY
  path, including the exception edges (GF3);
- which protocol frames/fault sites have live senders and handlers (GF4).

Shared infrastructure is reused from graftlint.core: ``SourceFile`` /
``Project`` / ``load_project``, ``Finding``, and the normalized
line-number-free ``[xN]`` baseline format (file:
``graftflow_baseline.txt``, checked in EMPTY).

Suppressions (both REQUIRE a non-empty reason or they are inert,
graftlint's escape semantics):

- ``# graftflow: ok(<reason>)`` on the finding line suppresses any GF
  rule there;
- ``# graftflow: ignore[GF201](<reason>)`` suppresses only the named
  rule(s).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from tools.graftlint.core import (Finding, Project, SourceFile,  # noqa: F401
                                  dotted_name, expr_text, load_project,
                                  normalize_expr, read_baseline, split_new,
                                  stale_entries, write_baseline)

BASELINE_NAME = "graftflow_baseline.txt"

_SUPPRESS_RE = re.compile(
    r"#\s*graftflow:\s*"
    r"(?:(ok)|ignore\[([A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*)\])"
    r"\(([^)]*)\)"
)


def suppressed(sf: SourceFile, rule: str, line: int) -> bool:
    """Whether ``rule`` is suppressed on ``line`` (trailing comment, or a
    standalone comment directly above).  A suppression with an EMPTY
    reason is deliberately inert: accepted debt must say why."""
    for m in _SUPPRESS_RE.finditer(sf._comment_for(line)):
        if not m.group(3).strip():
            continue  # reasonless suppressions don't count
        if m.group(1):
            return True
        if rule in re.split(r"\s*,\s*", m.group(2)):
            return True
    return False


# -- shared scope / registries ---------------------------------------------

# ``self.<field>`` -> owning class, for call-graph and lock resolution.
# The threaded serving core's collaborator fields (graftlint's GL401 map,
# widened to the whole runtime + cluster layer).
FIELD_CLASSES: dict[str, str] = {
    "pool": "PagePool",
    "prefix_cache": "PrefixCache",
    "batcher": "ContinuousBatcher",
    "faults": "FaultPlane",
    "fleet": "ReplicaFleet",
    "server": "InferenceServer",
    "router": "ReplicaRouter",
}

# Module-level globals whose methods resolve to a known class.
GLOBAL_CLASSES: dict[str, str] = {
    "METRICS": "Metrics",
}

# Module aliases: ``protocol.send_message(...)`` resolves to the function
# in the file whose stem matches.
MODULE_ALIASES = ("protocol", "kv_transfer", "faults", "batcher", "fleet")

# The modules whose interactions graftflow audits (repo-relative
# suffixes). Everything else is out of scope by design — the single-file
# rules live in graftlint.
SCOPE_SUFFIXES = (
    "runtime/batcher.py", "runtime/server.py", "runtime/router.py",
    "runtime/faults.py", "core/observability.py",
    "cluster/fleet.py", "cluster/kv_transfer.py", "cluster/protocol.py",
    "cluster/coordinator.py", "cluster/worker.py", "cluster/client.py",
    "cluster/metrics_http.py", "cluster/distributed.py",
)


def scope_files(project: Project) -> list[SourceFile]:
    """Package files graftflow analyzes.  Matching is by path suffix so
    the self-test fixture trees (pkg/runtime/..., pkg/cluster/...) land in
    scope exactly like the real package."""
    return [sf for sf in project.package_files()
            if sf.rel.endswith(SCOPE_SUFFIXES)]


# ONE parser for the module-level ``NAME = {str: str}`` registry idiom
# (FAULT_SITES / METRIC_DOCS / LOCK_ORDER): graftlint's GL3xx rules and
# graftflow must never disagree on what a registry contains.
from tools.graftlint.registry import _literal_dict as literal_strdict  # noqa: E402,F401


# -- function index / call graph -------------------------------------------

@dataclass(frozen=True)
class FnKey:
    rel: str            # repo-relative path of the defining file
    cls: str | None     # None = module-level function
    name: str

    def pretty(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


@dataclass
class FnInfo:
    key: FnKey
    sf: SourceFile
    node: ast.FunctionDef | ast.AsyncFunctionDef

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)


def collect_functions(files: list[SourceFile]) -> dict[FnKey, FnInfo]:
    """Top-level functions and one-level class methods (the shapes this
    tree uses; nested defs belong to their enclosing function's CFG)."""
    out: dict[FnKey, FnInfo] = {}
    for sf in files:
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                k = FnKey(sf.rel, None, node.name)
                out[k] = FnInfo(k, sf, node)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        k = FnKey(sf.rel, node.name, sub.name)
                        out[k] = FnInfo(k, sf, sub)
    return out


def local_aliases(fn: ast.AST) -> dict[str, str]:
    """{local name: collaborator class} for ``x = self.<known field>`` —
    one-step aliases, the idiom the hot loops use."""
    out: dict[str, str] = {}
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Attribute)
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "self"
                and node.value.attr in FIELD_CLASSES):
            out[node.targets[0].id] = FIELD_CLASSES[node.value.attr]
    return out


def resolve_call(call: ast.Call, caller: FnKey, aliases: dict[str, str],
                 fns: dict[FnKey, FnInfo]) -> list[FnKey]:
    """Callees a call site may reach, conservatively UNDER-approximated:
    unresolvable receivers contribute no edge (a missed edge can hide a
    finding but never invent one)."""
    f = call.func
    out: list[FnKey] = []

    def by(cls: str | None, name: str, rel: str | None = None) -> None:
        for k in fns:
            if k.name == name and k.cls == cls \
                    and (rel is None or k.rel == rel):
                out.append(k)

    if isinstance(f, ast.Name):
        # Module-level function in the SAME file (imports of single
        # functions across modules are rare in scope; by-name cross-file
        # resolution would invent edges between unrelated helpers).
        by(None, f.id, rel=caller.rel)
    elif isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Name):
            if v.id == "self":
                by(caller.cls, f.attr)
            elif v.id in aliases:
                by(aliases[v.id], f.attr)
            elif v.id in GLOBAL_CLASSES:
                by(GLOBAL_CLASSES[v.id], f.attr)
            elif v.id in MODULE_ALIASES:
                for k in fns:
                    if (k.name == f.attr and k.cls is None
                            and k.rel.endswith(f"/{v.id}.py")):
                        out.append(k)
        elif (isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name) and v.value.id == "self"
                and v.attr in FIELD_CLASSES):
            by(FIELD_CLASSES[v.attr], f.attr)
    return out


# -- control-flow graph ----------------------------------------------------

class Node:
    """One CFG node: a statement (or a synthetic entry/exit/join).
    ``succs`` are normal-flow successors; ``exc_succs`` are taken only
    when the statement raises."""

    __slots__ = ("stmt", "kind", "succs", "exc_succs")

    def __init__(self, stmt: ast.stmt | None, kind: str = "stmt") -> None:
        self.stmt = stmt
        self.kind = kind
        self.succs: list["Node"] = []
        self.exc_succs: list["Node"] = []

    def __repr__(self) -> str:  # debugging aid only
        at = getattr(self.stmt, "lineno", "-")
        return f"<{self.kind}@{at}>"


@dataclass
class Cfg:
    entry: Node
    exit: Node          # normal returns / fall-off-the-end
    raise_exit: Node    # an exception left the function
    nodes: list[Node] = field(default_factory=list)


def exec_parts(stmt: ast.stmt) -> list[ast.AST]:
    """The part of a statement its CFG node actually EXECUTES.  Compound
    statements execute only their header (test / iterable / context
    expressions) — their bodies are separate CFG nodes, and a predicate
    that walked the whole subtree would see nested cleanup/release code
    as if it ran unconditionally at the header."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.target, stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        out: list[ast.AST] = []
        for item in stmt.items:
            out.append(item.context_expr)
            if item.optional_vars is not None:
                out.append(item.optional_vars)
        return out
    if isinstance(stmt, ast.Try) or (
            hasattr(ast, "TryStar") and isinstance(stmt, ast.TryStar)):
        return []
    if isinstance(stmt, ast.Match):
        return [stmt.subject]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []  # a nested def runs when called, not where defined
    return [stmt]


# Attribute-call names that cannot realistically raise: bookkeeping on
# stdlib containers/events/locks and the metrics/logging registries.
# Pruning them keeps the exception-edge analyses focused on real raisers
# (submits, device calls, socket writes) instead of flagging every
# ``self._work.set()`` between an acquire and its release.
_INFALLIBLE_ATTRS = frozenset({
    "set", "clear", "inc", "observe", "set_gauge", "set_gauges",
    "append", "appendleft", "extend", "add", "discard", "update",
    "info", "warning", "error", "exception", "debug",
    "perf_counter", "monotonic", "time",
})
_INFALLIBLE_NAMES = frozenset({
    "range", "len", "enumerate", "zip", "isinstance", "list", "sorted",
    "id",
})


def _can_raise(node: ast.AST) -> bool:
    """Whether executing this code may raise: any call/await inside (the
    overwhelmingly dominant source) plus explicit raise/assert — except
    calls to the infallible bookkeeping methods/builtins above.
    Attribute/subscript misses exist but flagging them would drown the
    signal."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Await, ast.Raise, ast.Assert)):
            return True
        if isinstance(sub, ast.Call):
            if (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _INFALLIBLE_ATTRS):
                continue
            if (isinstance(sub.func, ast.Name)
                    and sub.func.id in _INFALLIBLE_NAMES):
                continue
            return True
    return False


def _catches_all(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True
    names = {n.attr if isinstance(n, ast.Attribute) else getattr(n, "id", "")
             for n in ([h.type] if not isinstance(h.type, ast.Tuple)
                       else h.type.elts)}
    return bool(names & {"BaseException", "Exception"})


class _CfgBuilder:
    def __init__(self) -> None:
        self.nodes: list[Node] = []
        self.exit = self._new(None, "exit")
        self.raise_exit = self._new(None, "raise-exit")
        # (head, after) per enclosing loop, for continue/break.
        self._loops: list[tuple[Node, Node]] = []

    def _new(self, stmt: ast.stmt | None, kind: str = "stmt") -> Node:
        n = Node(stmt, kind)
        self.nodes.append(n)
        return n

    def build(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> Cfg:
        entry = self._block(fn.body, self.exit, [self.raise_exit])
        return Cfg(entry=entry, exit=self.exit, raise_exit=self.raise_exit,
                   nodes=self.nodes)

    def _block(self, stmts: list[ast.stmt], follow: Node,
               exc: list[Node]) -> Node:
        nxt = follow
        for stmt in reversed(stmts):
            nxt = self._stmt(stmt, nxt, exc)
        return nxt

    def _stmt(self, stmt: ast.stmt, follow: Node, exc: list[Node]) -> Node:
        n = self._new(stmt)
        # Only the statement's EXECUTED part decides its exception edge —
        # a compound statement's body raises from its own nodes.
        raising = any(_can_raise(p) for p in exec_parts(stmt))

        if isinstance(stmt, ast.Return):
            n.succs = [self.exit]
            if raising:
                n.exc_succs = list(exc)
        elif isinstance(stmt, ast.Raise):
            n.succs = []
            n.exc_succs = list(exc)
        elif isinstance(stmt, ast.Break):
            n.succs = [self._loops[-1][1]] if self._loops else [follow]
        elif isinstance(stmt, ast.Continue):
            n.succs = [self._loops[-1][0]] if self._loops else [follow]
        elif isinstance(stmt, ast.If):
            body = self._block(stmt.body, follow, exc)
            orelse = self._block(stmt.orelse, follow, exc)
            n.succs = [body, orelse]
            if raising:
                n.exc_succs = list(exc)
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            after = self._block(getattr(stmt, "orelse", []), follow, exc)
            self._loops.append((n, follow))
            body = self._block(stmt.body, n, exc)
            self._loops.pop()
            n.succs = [body]
            infinite = (isinstance(stmt, ast.While)
                        and isinstance(stmt.test, ast.Constant)
                        and bool(stmt.test.value))
            if not infinite:
                n.succs.append(after)
            if raising:
                n.exc_succs = list(exc)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            body = self._block(stmt.body, follow, exc)
            n.succs = [body]
            if raising:  # the __enter__ call
                n.exc_succs = list(exc)
        elif isinstance(stmt, ast.Try) or (
                hasattr(ast, "TryStar") and isinstance(stmt, ast.TryStar)):
            # finally: built ONCE with a fork join — its exit reaches both
            # the normal follow and the exceptional continuation (an
            # over-approximation that never skips a cleanup node, which is
            # all the path analyses care about).
            if stmt.finalbody:
                join = self._new(None, "join")
                join.succs = [follow]
                join.exc_succs = list(exc)
                fin_entry = self._block(stmt.finalbody, join, exc)
                after_body, outer_exc = fin_entry, [fin_entry]
            else:
                after_body, outer_exc = follow, list(exc)
            handler_entries: list[Node] = []
            for h in stmt.handlers:
                handler_entries.append(
                    self._block(h.body, after_body, outer_exc))
            # A catch-all handler (bare except / except BaseException /
            # except Exception) means a body exception cannot skip past
            # the handlers to the outer context.
            inner_exc = handler_entries + (
                [] if any(_catches_all(h) for h in stmt.handlers)
                else outer_exc
            )
            orelse = self._block(stmt.orelse, after_body, inner_exc) \
                if stmt.orelse else after_body
            body = self._block(stmt.body, orelse, inner_exc)
            n.succs = [body]
        elif isinstance(stmt, ast.Match):
            n.succs = [self._block(case.body, follow, exc)
                       for case in stmt.cases] + [follow]
            if raising:
                n.exc_succs = list(exc)
        else:
            n.succs = [follow]
            if raising:
                n.exc_succs = list(exc)
        return n


def build_cfg(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> Cfg:
    return _CfgBuilder().build(fn)


def leaky_paths(start: Node, clears, exits: tuple[Node, ...]) -> Node | None:
    """May-path query: starting AFTER ``start``, is there a path to one of
    ``exits`` that never passes a node for which ``clears(node)`` is true?
    Returns the reached exit node (evidence) or None.

    A clearing node neutralizes ALL its outgoing edges — including its
    exception edges (once the sink statement runs, ownership moved, even
    if something later in the same expression raises).  Callers choose
    the exits that constitute a leak: GF301 passes both exits (an open
    page obligation must not survive ANY way out), GF303 passes only
    ``raise_exit`` (a registration is SUPPOSED to outlive a normal
    return)."""
    seen: set[int] = set()
    # Normal successors only: if the acquiring statement ITSELF raises,
    # the resource was never obtained and there is nothing to leak.
    stack: list[Node] = list(start.succs)
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        if node in exits:
            return node
        if node.kind == "stmt" and clears(node):
            continue
        stack += node.succs
        stack += node.exc_succs
    return None


def mentions_name(stmt: ast.stmt, name: str) -> bool:
    """Whether the statement's EXECUTED part (header only, for compound
    statements) mentions the local ``name``."""
    return any(isinstance(sub, ast.Name) and sub.id == name
               for part in exec_parts(stmt)
               for sub in ast.walk(part))
