"""GM4 — liveness and boundedness over the explored space.

- GM401: deadlock — a stuck state (no enabled transition) that fails
  the model's ``terminal`` predicate.  A bypassed request parked
  forever, a parcel nobody resumes, a drain that never finishes: each
  is a stuck non-terminal state, and the shortest trace to it is the
  reproduction;
- GM402: an invariant tagged ``GM4`` fails (size within [MIN, MAX],
  downs only via drain, bounded retries/streaks);
- GM403: a transition never enabled anywhere in the explored space —
  dead model entries are model rot exactly like dead registry entries
  (graftlint's GL305), and a guard that can never fire usually means
  the model no longer matches the code;
- GM404: the exploration tripped a divergence backstop (MAX_STATES or
  a variable leaving its bound) — the model is not finite, so nothing
  "exhaustive" can be claimed about it.  GM403 is skipped for such a
  model (the unexplored remainder could enable anything).
"""

from __future__ import annotations

from .core import Finding, ModelDecl
from .machine import ExploreResult, render_state, render_trace

RULE_DEADLOCK = "GM401"
RULE_INVARIANT = "GM402"
RULE_DEAD = "GM403"
RULE_UNBOUNDED = "GM404"


def check_explored(
        explored: list[tuple[ModelDecl, object, ExploreResult]],
) -> list[Finding]:
    out: list[Finding] = []
    for decl, _cm, res in explored:
        for v in res.violations:
            if v.kind == "deadlock":
                out.append(Finding(
                    RULE_DEADLOCK, decl.sf.rel,
                    decl.element_line("terminal"),
                    f"model '{decl.name}': deadlock — stuck state "
                    f"[{render_state(v.state)}] fails the terminal "
                    f"predicate — trace: {render_trace(v.trace)}",
                ))
            elif v.kind == "invariant" and v.rule_tag == "GM4":
                out.append(Finding(
                    RULE_INVARIANT, decl.sf.rel,
                    decl.element_line(v.key),
                    f"model '{decl.name}': invariant '{v.name}' violated "
                    f"at state [{render_state(v.state)}] — trace: "
                    f"{render_trace(v.trace)}",
                ))
        if res.overflow:
            out.append(Finding(
                RULE_UNBOUNDED, decl.sf.rel, decl.line,
                f"model '{decl.name}': exploration exceeded the state "
                f"bound after {res.states} states — bound every counter "
                f"with a budget param or the space is not exhaustive",
            ))
        elif res.diverged:
            out.append(Finding(
                RULE_UNBOUNDED, decl.sf.rel, decl.line,
                f"model '{decl.name}': {res.diverged} — bound every "
                f"counter with a budget param",
            ))
        else:
            for tr in res.never_enabled:
                out.append(Finding(
                    RULE_DEAD, decl.sf.rel,
                    decl.element_line(tr.key),
                    f"model '{decl.name}': transition '{tr.name}' is "
                    f"never enabled anywhere in the explored space "
                    f"(dead model entry — the guard can never fire)",
                ))
    return out
