"""graftmodel — exhaustive fault-interleaving model checking of the
fleet control plane.

The fifth static-analysis tier, and the first that proves rather than
scans: graftlint (PR 4) checks statements, graftcheck (PR 5) traces
tensor contracts, graftflow (PR 16) checks concurrency interactions,
graftsync (PR 19) audits lockstep determinism — graftmodel exhaustively
enumerates every bounded interleaving of the control-plane protocols
composed with their declared fault actions and checks the invariants the
fleet rests on.  The protocols live as machine-readable ``*_MODEL``
literals NEXT TO the code they model (registered in ``PROTOCOL_MODELS``,
runtime/faults.py):

- GM1xx ledger accounting            (tools/graftmodel/invariants.py)
- GM2xx parcel ownership             (tools/graftmodel/invariants.py)
- GM3xx at-most-once adoption        (tools/graftmodel/invariants.py)
- GM4xx liveness & boundedness       (tools/graftmodel/liveness.py)
- GM5xx model <-> code drift         (tools/graftmodel/drift.py)
- GM6xx drill coverage               (tools/graftmodel/drills.py)
- GMD01 README table drift           (tools/graftmodel/docs.py)

Run as ``python -m tools.graftmodel`` (exit 0 = clean) or through the
unified front door ``python -m tools.check``; the tier-1 pytest gate is
tests/tools/test_graftmodel.py::test_repo_is_clean.  Accepted debt lives
in ``graftmodel_baseline.txt`` (checked in EMPTY; graftlint's normalized
line-free multiset format) — a protocol invariant violation is a bug to
FIX, never debt to baseline.
"""

from __future__ import annotations

from pathlib import Path

from .core import (BASELINE_NAME, Finding, Project, discover_models,
                   load_project, load_registries, split_new, suppressed,
                   validate_model)
from tools.graftlint.core import read_baseline as _read_baseline
from tools.graftlint.core import write_baseline as _write_baseline

FAMILIES = ("GM1", "GM2", "GM3", "GM4", "GM5", "GM6", "GMD")

# Families whose findings come out of the shared per-model exploration.
_EXPLORE_FAMILIES = {"GM1", "GM2", "GM3", "GM4"}


def write_baseline(root, findings):
    return _write_baseline(Path(root), findings, name=BASELINE_NAME,
                           tool="graftmodel")


def read_baseline(root):
    return _read_baseline(Path(root), name=BASELINE_NAME)


def run_project(project: Project, only: set[str] | None = None,
                stats: list[dict] | None = None) -> list[Finding]:
    """Run every rule family (or the ``only`` subset of FAMILIES).

    One BFS per valid model feeds all four invariant families; pass a
    ``stats`` list to receive ``{"model", "states", "fired"}`` per
    explored model (the CLI prints them, the bench records them).
    """
    from . import docs, drift, drills, invariants, liveness
    from .machine import compile_model, explore

    def want(fam: str) -> bool:
        return only is None or fam in only

    decls, schema_findings = discover_models(project)
    valid: list = []
    for decl in decls:
        errs = validate_model(decl)
        schema_findings += errs
        if not errs:
            valid.append(decl)
    regs = load_registries(project)

    findings: list[Finding] = []
    if any(want(f) for f in _EXPLORE_FAMILIES):
        explored = []
        for decl in valid:
            cm = compile_model(decl)
            res = explore(cm)
            explored.append((decl, cm, res))
            if stats is not None:
                stats.append({"model": decl.name, "states": res.states,
                              "fired": res.fired})
        inv = invariants.check_explored(explored)
        live = liveness.check_explored(explored)
        if want("GM1"):
            findings += [f for f in inv if f.rule == "GM101"]
        if want("GM2"):
            findings += [f for f in inv if f.rule == "GM201"]
        if want("GM3"):
            findings += [f for f in inv if f.rule == "GM301"]
            findings += invariants.check_metrics_declared(valid)
        if want("GM4"):
            findings += live
    if want("GM5"):
        findings += drift.check(decls, regs)
        findings += schema_findings
    if want("GM6"):
        findings += drills.check(project, regs)
    if want("GMD"):
        findings += docs.check_docs(project.root, decls, regs)

    by_rel = {sf.rel: sf for sf in project.files}
    findings = [f for f in findings
                if f.path not in by_rel
                or not suppressed(by_rel[f.path], f.rule, f.line)]
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.message))


def run(root, only: set[str] | None = None,
        stats: list[dict] | None = None) -> list[Finding]:
    return run_project(load_project(root), only=only, stats=stats)


__all__ = [
    "BASELINE_NAME", "FAMILIES", "Finding", "Project", "load_project",
    "read_baseline", "run", "run_project", "split_new", "write_baseline",
]
