"""The explicit-state explorer: exhaustive BFS over a model's bounded
interleaving space.

One compiled model = the composition of its protocol actions and its
declared fault actions; one state = the tuple of state-variable values.
BFS from the initial state explores EVERY enabled transition of every
reachable state — exhaustive, not sampled, which is the entire point:
a chaos storm answers "did this ordering break?", the explorer answers
"is there ANY ordering that breaks?".  BFS also makes every reported
trace a shortest counterexample, and fixed transition order makes runs
byte-deterministic (baseline-stable messages).

Guards and updates are compiled once per model and evaluated with empty
``__builtins__`` over ``params`` + the state — a model cannot reach the
filesystem, the clock, or the repo under analysis.  Updates all read the
PRE-state (simultaneous assignment, the TLA+ convention).

Divergence backstops (GM404, not tuning knobs): exploration stops at
``MAX_STATES`` states, and any variable leaving ``[-VAR_BOUND,
VAR_BOUND]`` aborts — a model with an unbounded counter is a bug in the
model, and silently truncating the space would turn "exhaustively
verified" into a lie.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from .core import MAX_STATES, VAR_BOUND, ModelDecl

_EMPTY_BUILTINS = {"__builtins__": {}}
_TRACE_SHOWN = 14  # max transitions rendered in a counterexample trace


@dataclass
class Transition:
    name: str
    kind: str                    # "action" | "fault"
    index: int                   # position within its decl list
    guard: object                # code object
    updates: list[tuple[str, object]]
    site: str | None = None
    action: str | None = None
    metric: str | None = None

    @property
    def key(self) -> str:
        return f"{self.kind}s[{self.index}]"


@dataclass
class CompiledModel:
    decl: ModelDecl
    params: dict[str, int]
    var_names: tuple[str, ...]   # fixed order = state tuple order
    start: tuple[int, ...]
    transitions: list[Transition]
    invariants: list[tuple[str, str, object, str]]  # (rule, name, code, key)
    terminal: object


def compile_model(decl: ModelDecl) -> CompiledModel:
    """Assumes the decl already passed :func:`core.validate_model`."""
    d = decl.data
    var_names = tuple(sorted(d["state"]))
    transitions: list[Transition] = []
    for kind in ("action", "fault"):
        for i, tr in enumerate(d[f"{kind}s"]):
            transitions.append(Transition(
                name=tr["name"], kind=kind, index=i,
                guard=compile(tr["guard"], "<graftmodel>", "eval"),
                updates=[(v, compile(e, "<graftmodel>", "eval"))
                         for v, e in tr["update"].items()],
                site=tr.get("site"), action=tr.get("action"),
                metric=tr.get("metric"),
            ))
    invariants = [
        (inv["rule"], inv["name"],
         compile(inv["expr"], "<graftmodel>", "eval"),
         f"invariants[{i}]")
        for i, inv in enumerate(d["invariants"])
    ]
    return CompiledModel(
        decl=decl, params=dict(d["params"]), var_names=var_names,
        start=tuple(d["state"][v] for v in var_names),
        transitions=transitions, invariants=invariants,
        terminal=compile(d["terminal"], "<graftmodel>", "eval"),
    )


@dataclass
class Violation:
    kind: str                    # "invariant" | "deadlock"
    rule_tag: str                # invariant rule tag ("GM1"...) or ""
    name: str                    # invariant name or ""
    key: str                     # decl element key for line/suppression
    state: dict[str, int]
    trace: list[str]


@dataclass
class ExploreResult:
    states: int = 0
    fired: int = 0               # transition firings (state x transition)
    violations: list[Violation] = field(default_factory=list)
    never_enabled: list[Transition] = field(default_factory=list)
    overflow: bool = False       # MAX_STATES exceeded
    diverged: str | None = None  # "var 'x' left [-N, N] via 'name'"


def _trace(parents: dict, state: tuple) -> list[str]:
    out: list[str] = []
    cur = state
    while parents.get(cur) is not None:
        cur, name = parents[cur]
        out.append(name)
    out.reverse()
    if len(out) > _TRACE_SHOWN:
        out = [f"... {len(out) - _TRACE_SHOWN} more"] + out[-_TRACE_SHOWN:]
    return out


def explore(cm: CompiledModel, max_states: int = MAX_STATES) -> ExploreResult:
    """Exhaustive BFS.  Reports the FIRST (shortest-trace) violation per
    invariant and the first deadlock — one counterexample per law is
    actionable; ten thousand are noise."""
    res = ExploreResult()
    names = cm.var_names
    parents: dict[tuple, tuple | None] = {cm.start: None}
    queue: deque[tuple] = deque([cm.start])
    seen_inv: set[str] = set()
    enabled_ever: set[str] = set()
    deadlocked = False

    while queue:
        s = queue.popleft()
        env = dict(cm.params)
        env.update(zip(names, s))
        for rule, iname, code, key in cm.invariants:
            if iname not in seen_inv and not eval(code, _EMPTY_BUILTINS, env):
                seen_inv.add(iname)
                res.violations.append(Violation(
                    kind="invariant", rule_tag=rule, name=iname, key=key,
                    state=dict(zip(names, s)), trace=_trace(parents, s)))
        any_enabled = False
        for tr in cm.transitions:
            if not eval(tr.guard, _EMPTY_BUILTINS, env):
                continue
            any_enabled = True
            enabled_ever.add(tr.name)
            res.fired += 1
            nxt = dict(zip(names, s))
            for var, code in tr.updates:
                val = nxt[var] = eval(code, _EMPTY_BUILTINS, env)
                if not isinstance(val, int) or isinstance(val, bool) \
                        or abs(val) > VAR_BOUND:
                    res.diverged = (f"variable '{var}' left "
                                    f"[-{VAR_BOUND}, {VAR_BOUND}] (or went "
                                    f"non-int) via '{tr.name}'")
                    res.states = len(parents)
                    res.never_enabled = [
                        t for t in cm.transitions
                        if t.name not in enabled_ever]
                    return res
            ns = tuple(nxt[v] for v in names)
            if ns not in parents:
                if len(parents) >= max_states:
                    res.overflow = True
                    res.states = len(parents)
                    res.never_enabled = [
                        t for t in cm.transitions
                        if t.name not in enabled_ever]
                    return res
                parents[ns] = (s, tr.name)
                queue.append(ns)
        if not any_enabled and not deadlocked \
                and not eval(cm.terminal, _EMPTY_BUILTINS, env):
            deadlocked = True
            res.violations.append(Violation(
                kind="deadlock", rule_tag="", name="", key="terminal",
                state=dict(zip(names, s)), trace=_trace(parents, s)))

    res.states = len(parents)
    res.never_enabled = [t for t in cm.transitions
                         if t.name not in enabled_ever]
    return res


def render_state(state: dict[str, int]) -> str:
    return " ".join(f"{k}={v}" for k, v in sorted(state.items()))


def render_trace(trace: list[str]) -> str:
    return " -> ".join(trace) if trace else "<initial state>"
