"""CLI: ``python -m tools.graftmodel [--root DIR] [--only GM1,GM5]``.

Exit status mirrors the other four tiers: 0 when every finding is absent
or baselined, 1 when NEW findings exist, 2 on usage errors.

- ``--only``: comma-separated rule families (GM1..GM6, GMD) — scoped
  runs for fast iteration; the gate and the front door run everything.
- ``--baseline-write``: accept current findings into
  ``graftmodel_baseline.txt`` (protocol invariant violations should be
  FIXED, not baselined — the file ships empty).
- ``--write-docs``: regenerate the README models + rules tables.
- ``--all``: also print baselined findings.

Pure AST + in-memory BFS over ``--root``: no imports of the analyzed
code, no devices.  Per-model explored-state counts go to stderr so
"exhaustive" is a number you can watch, not an adjective.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftmodel",
        description="exhaustive fault-interleaving model checking "
                    "(see tools/graftmodel/)",
    )
    ap.add_argument("--root", default=".", help="repo root to analyze")
    ap.add_argument("--only", default=None,
                    help="comma-separated families, e.g. GM1,GM5")
    ap.add_argument("--baseline-write", action="store_true",
                    help="accept current findings into the baseline")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate the README model/rule tables, then exit")
    ap.add_argument("--all", action="store_true",
                    help="also print baselined (accepted) findings")
    args = ap.parse_args(argv)

    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"graftmodel: --root {root} is not a directory",
              file=sys.stderr)
        return 2

    from tools.graftmodel import (FAMILIES, load_project, read_baseline,
                                  run_project, split_new, write_baseline)

    only = None
    if args.only:
        only = {s.strip() for s in args.only.split(",") if s.strip()}
        unknown = only - set(FAMILIES)
        if unknown:
            print(f"graftmodel: unknown families {sorted(unknown)}; "
                  f"have {FAMILIES}", file=sys.stderr)
            return 2

    project = load_project(root)

    if args.write_docs:
        from tools.graftmodel.core import discover_models, load_registries
        from tools.graftmodel.docs import write_docs

        decls, _ = discover_models(project)
        done = write_docs(root, decls, load_registries(project))
        print("graftmodel: rewrote README model/rule tables" if done
              else "graftmodel: no graftmodel marker blocks found")
        return 0

    stats: list[dict] = []
    findings = run_project(project, only=only, stats=stats)
    for s in stats:
        print(f"graftmodel: model '{s['model']}': {s['states']} states, "
              f"{s['fired']} transitions explored", file=sys.stderr)
    if args.baseline_write:
        path = write_baseline(root, findings)
        print(f"graftmodel: wrote {len(findings)} finding(s) to {path.name}")
        return 0

    baseline = read_baseline(root)
    new, accepted = split_new(findings, baseline)
    for f in new:
        print(f.render())
    if args.all:
        for f in accepted:
            print(f"{f.render()}  [baselined]")
    from tools.graftlint.core import stale_entries

    stale = stale_entries(findings, baseline)
    print(f"graftmodel: {len(new)} new finding(s), {len(accepted)} "
          f"baselined, {len(stale)} stale baseline "
          f"entr{'y' if len(stale) == 1 else 'ies'}", file=sys.stderr)
    for s in stale:
        print(f"  stale: {s}", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
