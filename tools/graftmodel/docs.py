"""GMD01 — README table drift.

The README's "Protocol model checking" section carries TWO generated
tables between marker comments (the established convention):

- ``<!-- graftmodel:models:begin/end -->`` — the checked models, rendered
  from PROTOCOL_MODELS plus each discovered ``*_MODEL`` literal (where it
  lives, how big it is, its one-line doc);
- ``<!-- graftmodel:rules:begin/end -->`` — the GM rule families,
  rendered from :data:`RULE_DOCS`.

``python -m tools.graftmodel --write-docs`` regenerates both; GMD01
fails the gate when either diverges — a model added without a README row
(or a README row outliving its model) is registry drift in prose form.
"""

from __future__ import annotations

import re
from pathlib import Path

from .core import Finding, ModelDecl, Registries

RULE_DRIFT = "GMD01"

# rule id -> (family, one-line contract).  The README rules table renders
# from this dict; keep entries in rule order.
RULE_DOCS: dict[str, tuple[str, str]] = {
    "GM101": ("GM1 ledger accounting",
              "no reachable state violates a GM1-tagged invariant "
              "(quota conservation, charge-iff-placed, no lost refund, "
              "bounded backstop metering) — reported with the shortest "
              "counterexample trace"),
    "GM201": ("GM2 parcel ownership",
              "no reachable state violates a GM2-tagged invariant "
              "(every parked swap/spill parcel owned by exactly one "
              "queued resume, page budget conserved and never "
              "oversubscribed)"),
    "GM301": ("GM3 at-most-once adoption",
              "no reachable state violates a GM3-tagged invariant "
              "(a KV handoff or directory pull is adopted at most once, "
              "every fallback counted exactly once)"),
    "GM302": ("GM3 at-most-once adoption",
              "every fault edge declares the per-reason fallback metric "
              "its recovery path increments"),
    "GM401": ("GM4 liveness",
              "no deadlock: every stuck state (no enabled transition) "
              "satisfies the model's terminal predicate"),
    "GM402": ("GM4 liveness",
              "no reachable state violates a GM4-tagged invariant "
              "(fleet size within [MIN, MAX], scale-down only via "
              "drain, retries/streaks bounded)"),
    "GM403": ("GM4 liveness",
              "every declared transition is enabled somewhere in the "
              "explored space — a guard that can never fire is model "
              "rot"),
    "GM404": ("GM4 liveness",
              "exploration terminates within the divergence backstops — "
              "an unbounded counter makes 'exhaustive' a lie"),
    "GM501": ("GM5 model-code drift",
              "every fault edge's site:action pair is declared in "
              "FAULT_SITES / SITE_ACTIONS — the model only drills "
              "faults the fault plane can inject"),
    "GM502": ("GM5 model-code drift",
              "every fault edge's metric is declared in METRIC_DOCS "
              "(wildcard patterns match)"),
    "GM503": ("GM5 model-code drift",
              "PROTOCOL_MODELS and *_MODEL literals agree both "
              "directions; SITE_ACTIONS and FAULT_SITES keys agree both "
              "directions; SITE_ACTIONS tokens stay inside the ACTIONS "
              "grammar; model names are unique"),
    "GM504": ("GM5 model-code drift",
              "every *_MODEL assignment is a pure dict literal matching "
              "the schema (state/params typed, guards and updates "
              "compile, no undeclared variables, invariant tags in "
              "GM1..GM4)"),
    "GM601": ("GM6 drill coverage",
              "every SITE_ACTIONS pair is injected by at least one "
              "tier-1 test (spec strings or plane.add with literal "
              "args) — a declared-but-never-drilled fault is an "
              "untested recovery path"),
    "GMD01": ("GMD docs",
              "the README models and GM-rules tables match the "
              "registries and RULE_DOCS — run python -m tools.graftmodel "
              "--write-docs"),
}

_MODELS_RE = re.compile(
    r"<!-- graftmodel:models:begin -->\n(.*?)<!-- graftmodel:models:end -->",
    re.S,
)
_RULES_RE = re.compile(
    r"<!-- graftmodel:rules:begin -->\n(.*?)<!-- graftmodel:rules:end -->",
    re.S,
)


def render_models_table(decls: list[ModelDecl],
                        regs: Registries) -> str:
    by_name = {d.name: d for d in decls}
    lines = ["| model | declared in | machine | checks |",
             "| --- | --- | --- | --- |"]
    for key in regs.protocol_models:
        d = by_name.get(key)
        if d is None:
            lines.append(f"| `{key}` | *(unregistered — GM503)* | | |")
            continue
        data = d.data
        size = (f"{len(data.get('actions', []))} actions + "
                f"{len(data.get('faults', []))} faults, "
                f"{len(data.get('invariants', []))} invariants")
        doc = data.get("doc", "") if isinstance(data.get("doc"), str) else ""
        lines.append(f"| `{key}` | `{d.sf.rel}` (`{d.var}`) | {size} "
                     f"| {doc} |")
    return "\n".join(lines)


def render_rules_table() -> str:
    lines = ["| rule | family | checks |", "| --- | --- | --- |"]
    lines += [f"| {rule} | {fam} | {doc} |"
              for rule, (fam, doc) in RULE_DOCS.items()]
    return "\n".join(lines)


def check_docs(root: Path, decls: list[ModelDecl],
               regs: Registries) -> list[Finding]:
    readme = root / "README.md"
    if not readme.exists():
        return []
    text = readme.read_text(encoding="utf-8")
    out: list[Finding] = []
    for marker_re, tag, want in (
            (_MODELS_RE, "models", render_models_table(decls, regs)),
            (_RULES_RE, "rules", render_rules_table())):
        m = marker_re.search(text)
        if m is None:
            out.append(Finding(
                RULE_DRIFT, "README.md", 1,
                f"missing '<!-- graftmodel:{tag}:begin/end -->' block — "
                f"run python -m tools.graftmodel --write-docs",
            ))
        elif m.group(1).strip() != want.strip():
            line = text[: m.start()].count("\n") + 1
            out.append(Finding(
                RULE_DRIFT, "README.md", line,
                f"graftmodel {tag} table is stale — run python -m "
                f"tools.graftmodel --write-docs",
            ))
    return out


def write_docs(root: Path, decls: list[ModelDecl],
               regs: Registries) -> bool:
    readme = root / "README.md"
    if not readme.exists():
        return False
    text = readme.read_text(encoding="utf-8")
    wrote = False
    for marker_re, tag, body in (
            (_MODELS_RE, "models", render_models_table(decls, regs)),
            (_RULES_RE, "rules", render_rules_table())):
        if marker_re.search(text) is None:
            continue
        block = (f"<!-- graftmodel:{tag}:begin -->\n{body}\n"
                 f"<!-- graftmodel:{tag}:end -->")
        # Callable replacement: table text must never be read as re escapes.
        text = marker_re.sub(lambda _m: block, text)
        wrote = True
    if wrote:
        readme.write_text(text, encoding="utf-8")
    return wrote
