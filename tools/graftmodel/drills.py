"""GM6 — drill coverage: every declared fault pair is exercised.

SITE_ACTIONS is the contract between the fault plane and the test
suite: each ``site -> actions`` entry says "the code around this site
handles these failure modes".  graftmodel proves the *protocol* survives
each fault action; GM601 closes the other half of the loop by requiring
that at least one tier-1 test actually injects each declared pair — a
declared-but-never-drilled pair is an untested recovery path wearing a
tested one's label.

The scan is static (same spirit as the rest of the tier): it walks the
test tree's ASTs for the two injection idioms —

- fault-plane spec strings: ``"xfer.send/KV:corrupt@2"`` inside any
  string literal (comma-separated specs, ``/qualifier`` and ``@when``
  ignored);
- programmatic rules: ``plane.add("xfer.send", "corrupt", ...)`` with
  literal string arguments.

Only sites present in FAULT_SITES count — tests also drill synthetic
sites (``"s:drop"``) to test the plane itself, and those are not
coverage of any declared pair.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Project, Registries

RULE_UNDRILLED = "GM601"

_SPEC_RE = re.compile(
    r"([a-z_][a-z0-9_.]*)(?:/[A-Za-z0-9_*+-]+)?:([a-z]+)")


def drilled_pairs(project: Project,
                  regs: Registries) -> dict[tuple[str, str], str]:
    """``(site, action) -> "rel:line"`` of one test that injects it."""
    out: dict[tuple[str, str], str] = {}

    def record(site: str, action: str, rel: str, line: int) -> None:
        if site in regs.fault_sites:
            out.setdefault((site, action), f"{rel}:{line}")

    for sf in project.test_files():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                for m in _SPEC_RE.finditer(node.value):
                    record(m.group(1), m.group(2), sf.rel, node.lineno)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "add" \
                    and len(node.args) >= 2 \
                    and all(isinstance(a, ast.Constant)
                            and isinstance(a.value, str)
                            for a in node.args[:2]):
                record(node.args[0].value, node.args[1].value,
                       sf.rel, node.lineno)
    return out


def check(project: Project, regs: Registries) -> list[Finding]:
    if regs.faults_sf is None:
        return []
    drilled = drilled_pairs(project, regs)
    out: list[Finding] = []
    for site, acts in regs.site_actions.items():
        for action in sorted(a.strip() for a in acts.split(",") if a.strip()):
            if (site, action) in drilled:
                continue
            out.append(Finding(
                RULE_UNDRILLED, regs.faults_sf.rel,
                regs.site_lines.get(site, 1),
                f"declared fault pair '{site}:{action}' is never injected "
                f"by any test — write a drill or stop declaring the pair",
            ))
    return out
