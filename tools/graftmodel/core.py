"""graftmodel core: model discovery, schema, registries, suppressions.

graftmodel is the fifth static-analysis tier and the first that reasons
about *distributed interleavings* rather than single-process code.  The
fleet control plane's correctness rests on protocol invariants — ledger
quota conservation, exactly-one-owner parcels, at-most-once KV adoption,
graceful-drain-only scale-downs — that chaos storms only sample.  The
protocols are therefore declared as machine-readable transition systems
NEXT TO the code they model (module-level ``*_MODEL`` dict literals,
registered in ``PROTOCOL_MODELS`` in ``runtime/faults.py``), and
``python -m tools.graftmodel`` exhaustively enumerates every bounded
interleaving of each machine composed with its declared fault actions
(``SITE_ACTIONS``), checking the GM invariant families on every
reachable state — SPIN/TLA-style explicit-state exploration at the
state-space sizes these protocols actually have.

A model literal's schema (all guards/updates are Python expressions over
``params`` + ``state``, evaluated with no builtins):

- ``name``: the PROTOCOL_MODELS registry key;
- ``doc``: one line, rendered into the README models table;
- ``params``: bound constants (retry budgets, quotas, tick budgets);
- ``state``: initial variable values (ints);
- ``actions``: ``{name, guard, update: {var: expr}}`` protocol steps;
- ``faults``: the same plus ``site``/``action`` (a SITE_ACTIONS pair)
  and ``metric`` (the per-reason fallback counter the recovery path
  increments — must exist in METRIC_DOCS);
- ``invariants``: ``{rule: GM1..GM4, name, expr}`` — checked on every
  reachable state;
- ``terminal``: the predicate every stuck state (no enabled transition)
  must satisfy, or it is a deadlock (GM401).

Suppressions (both REQUIRE a non-empty reason or they are inert,
graftlint's escape semantics):

- ``# graftmodel: ok(<reason>)`` on the finding line suppresses any GM
  rule there;
- ``# graftmodel: ignore[GM101](<reason>)`` suppresses only the named
  rule(s).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from tools.graftlint.core import (Finding, Project, SourceFile,  # noqa: F401
                                  load_project, read_baseline, split_new,
                                  stale_entries, write_baseline)
from tools.graftlint.registry import _literal_dict as literal_strdict

BASELINE_NAME = "graftmodel_baseline.txt"

_SUPPRESS_RE = re.compile(
    r"#\s*graftmodel:\s*"
    r"(?:(ok)|ignore\[([A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*)\])"
    r"\(([^)]*)\)"
)

# The registry module (runtime/faults.py) and the registries graftmodel
# reads from it, parsed with graftlint's registry parser so the tools can
# never disagree on what a registry contains.
REGISTRY_MODULE = "runtime/faults.py"
MODELS_NAME = "PROTOCOL_MODELS"
SITE_ACTIONS_NAME = "SITE_ACTIONS"
FAULT_SITES_NAME = "FAULT_SITES"
METRICS_MODULE = "core/observability.py"
METRICS_NAME = "METRIC_DOCS"

# Exploration bounds: a model is supposed to be FINITE by construction
# (budget counters in its guards); these are divergence backstops, not
# tuning knobs — tripping either is a GM404 finding.
MAX_STATES = 300_000
VAR_BOUND = 10_000

_MODEL_KEYS = {"name", "doc", "params", "state", "actions", "faults",
               "invariants", "terminal"}
_INVARIANT_RULES = ("GM1", "GM2", "GM3", "GM4")
_RULE_OF_TAG = {"GM1": "GM101", "GM2": "GM201", "GM3": "GM301",
                "GM4": "GM402"}


def suppressed(sf: SourceFile, rule: str, line: int) -> bool:
    """Whether ``rule`` is suppressed on ``line`` (trailing comment, or a
    standalone comment directly above).  A suppression with an EMPTY
    reason is deliberately inert: accepted protocol debt must say why."""
    for m in _SUPPRESS_RE.finditer(sf._comment_for(line)):
        if not m.group(3).strip():
            continue  # reasonless suppressions don't count
        if m.group(1):
            return True
        if rule in re.split(r"\s*,\s*", m.group(2)):
            return True
    return False


@dataclass
class ModelDecl:
    """One discovered ``*_MODEL`` literal: parsed data plus the source
    line of every element a finding may attach to."""

    sf: SourceFile
    var: str                     # the assigned name, e.g. "LEDGER_MODEL"
    data: dict
    line: int                    # the assignment line
    # element key -> source line: "actions[3]", "faults[0]",
    # "invariants[2]" (findings attach to the element, suppressions too).
    lines: dict[str, int] = field(default_factory=dict)

    @property
    def name(self) -> str:
        n = self.data.get("name")
        return n if isinstance(n, str) else self.var

    def element_line(self, key: str) -> int:
        return self.lines.get(key, self.line)


def _element_lines(value: ast.Dict) -> dict[str, int]:
    out: dict[str, int] = {}
    for k, v in zip(value.keys, value.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            continue
        if k.value in ("actions", "faults", "invariants") \
                and isinstance(v, (ast.List, ast.Tuple)):
            for i, elt in enumerate(v.elts):
                out[f"{k.value}[{i}]"] = elt.lineno
        else:
            out[k.value] = v.lineno
    return out


def discover_models(project: Project) -> tuple[list[ModelDecl],
                                               list[Finding]]:
    """Every module-level ``*_MODEL = {...}`` literal in the shipped
    package.  A ``*_MODEL`` assignment that is not a pure literal is a
    GM504 finding — the whole point of the declaration is that a tool
    can read it without importing anything."""
    decls: list[ModelDecl] = []
    findings: list[Finding] = []
    for sf in project.package_files():
        for node in sf.tree.body:
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target] if isinstance(node, ast.AnnAssign)
                       else [])
            for t in targets:
                if not (isinstance(t, ast.Name)
                        and t.id.endswith("_MODEL")):
                    continue
                try:
                    data = ast.literal_eval(node.value)
                except (ValueError, TypeError):
                    findings.append(Finding(
                        "GM504", sf.rel, node.lineno,
                        f"model '{t.id}' is not a pure literal — the "
                        f"checker must read it without importing",
                    ))
                    continue
                if not isinstance(data, dict):
                    findings.append(Finding(
                        "GM504", sf.rel, node.lineno,
                        f"model '{t.id}' must be a dict literal",
                    ))
                    continue
                decls.append(ModelDecl(
                    sf=sf, var=t.id, data=data, line=node.lineno,
                    lines=_element_lines(node.value)
                    if isinstance(node.value, ast.Dict) else {},
                ))
    decls.sort(key=lambda d: (d.sf.rel, d.line))
    return decls, findings


def validate_model(decl: ModelDecl) -> list[Finding]:
    """GM504: schema errors — missing/unknown keys, non-compiling guard
    or update expressions, updates to undeclared variables, invariant
    rule tags outside GM1..GM4, fault edges without site/action."""
    out: list[Finding] = []
    d = decl.data

    def bad(msg: str, key: str | None = None) -> None:
        out.append(Finding(
            "GM504", decl.sf.rel,
            decl.element_line(key) if key else decl.line,
            f"model '{decl.name}': {msg}"))

    missing = _MODEL_KEYS - set(d)
    if missing:
        bad(f"missing keys {sorted(missing)}")
        return out
    unknown = set(d) - _MODEL_KEYS
    if unknown:
        bad(f"unknown keys {sorted(unknown)}")
    if not (isinstance(d["state"], dict) and d["state"]
            and all(isinstance(k, str) and isinstance(v, int)
                    and not isinstance(v, bool)
                    for k, v in d["state"].items())):
        bad("'state' must be a non-empty {var: int} dict", "state")
        return out
    if not (isinstance(d["params"], dict)
            and all(isinstance(k, str) and isinstance(v, int)
                    for k, v in d["params"].items())):
        bad("'params' must be a {name: int} dict", "params")
        return out
    shadow = set(d["state"]) & set(d["params"])
    if shadow:
        bad(f"state vars shadow params: {sorted(shadow)}", "state")

    def check_expr(expr, what: str, key: str) -> None:
        if not isinstance(expr, str):
            bad(f"{what} must be a str expression", key)
            return
        try:
            compile(expr, "<graftmodel>", "eval")
        except SyntaxError as e:
            bad(f"{what} does not compile: {e.msg}", key)

    seen_names: set[str] = set()
    for kind in ("actions", "faults"):
        if not isinstance(d[kind], list):
            bad(f"'{kind}' must be a list", kind)
            return out
        for i, tr in enumerate(d[kind]):
            key = f"{kind}[{i}]"
            if not isinstance(tr, dict) or not isinstance(
                    tr.get("name"), str):
                bad(f"{kind}[{i}] must be a dict with a 'name'", key)
                continue
            tname = tr["name"]
            if tname in seen_names:
                bad(f"duplicate transition name '{tname}'", key)
            seen_names.add(tname)
            check_expr(tr.get("guard"), f"transition '{tname}' guard", key)
            upd = tr.get("update")
            if not isinstance(upd, dict):
                bad(f"transition '{tname}' needs an 'update' dict", key)
                continue
            for var, expr in upd.items():
                if var not in d["state"]:
                    bad(f"transition '{tname}' updates undeclared "
                        f"variable '{var}'", key)
                check_expr(expr, f"transition '{tname}' update of "
                                 f"'{var}'", key)
            if kind == "faults":
                if not (isinstance(tr.get("site"), str)
                        and isinstance(tr.get("action"), str)):
                    bad(f"fault edge '{tname}' needs 'site' and "
                        f"'action'", key)
    if not isinstance(d["invariants"], list):
        bad("'invariants' must be a list", "invariants")
        return out
    for i, inv in enumerate(d["invariants"]):
        key = f"invariants[{i}]"
        if not isinstance(inv, dict) or not isinstance(
                inv.get("name"), str):
            bad(f"invariants[{i}] must be a dict with a 'name'", key)
            continue
        if inv.get("rule") not in _INVARIANT_RULES:
            bad(f"invariant '{inv['name']}' rule tag must be one of "
                f"{_INVARIANT_RULES}", key)
        check_expr(inv.get("expr"), f"invariant '{inv['name']}'", key)
    check_expr(d["terminal"], "'terminal'", "terminal")
    return out


# -- registries --------------------------------------------------------------

def _find_module(project: Project, suffix: str) -> SourceFile | None:
    return next((f for f in project.files if f.rel.endswith(suffix)), None)


@dataclass
class Registries:
    faults_sf: SourceFile | None
    metrics_sf: SourceFile | None
    protocol_models: dict[str, str]
    site_actions: dict[str, str]
    fault_sites: dict[str, str]
    metric_docs: dict[str, str]
    # registry entry key -> source line (for findings/suppressions)
    model_lines: dict[str, int] = field(default_factory=dict)
    site_lines: dict[str, int] = field(default_factory=dict)


def _entry_lines(sf: SourceFile, name: str) -> dict[str, int]:
    for node in sf.tree.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name \
                    and isinstance(node.value, ast.Dict):
                return {k.value: k.lineno for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
    return {}


def load_registries(project: Project) -> Registries:
    faults = _find_module(project, REGISTRY_MODULE)
    metrics = _find_module(project, METRICS_MODULE)
    return Registries(
        faults_sf=faults,
        metrics_sf=metrics,
        protocol_models=(literal_strdict(faults, MODELS_NAME) or {}
                         if faults else {}),
        site_actions=(literal_strdict(faults, SITE_ACTIONS_NAME) or {}
                      if faults else {}),
        fault_sites=(literal_strdict(faults, FAULT_SITES_NAME) or {}
                     if faults else {}),
        metric_docs=(literal_strdict(metrics, METRICS_NAME) or {}
                     if metrics else {}),
        model_lines=_entry_lines(faults, MODELS_NAME) if faults else {},
        site_lines=_entry_lines(faults, SITE_ACTIONS_NAME)
        if faults else {},
    )


def metric_registered(name: str, registry: dict[str, str]) -> bool:
    """GL302's matching: a literal entry, or a ``*`` pattern entry that
    the name matches (``router.handoff_fallbacks.verify`` is covered by
    ``router.handoff_fallbacks.*``)."""
    import fnmatch

    if name in registry:
        return True
    return any("*" in key and fnmatch.fnmatchcase(name, key)
               for key in registry)
