"""GM5 — model <-> code drift, both directions.

The models are only worth trusting while they stay pinned to the
registries the running code actually uses:

- GM501: a fault edge names a ``site:action`` that FAULT_SITES /
  SITE_ACTIONS (runtime/faults.py) does not declare — the model drills
  a fault the fault plane cannot inject;
- GM502: a fault edge's fallback metric is not declared in METRIC_DOCS
  (``*`` patterns match, GL302's semantics) — the modeled recovery
  path counts into a counter that does not exist;
- GM503: registry drift both directions — a PROTOCOL_MODELS entry with
  no ``*_MODEL`` declaration (dead registry entry), a model literal
  missing from PROTOCOL_MODELS, duplicate model names, SITE_ACTIONS
  keys that mismatch FAULT_SITES keys (either direction), and
  SITE_ACTIONS action tokens outside the fault plane's ACTIONS
  grammar;
- GM504: a ``*_MODEL`` assignment that is not a pure literal, or fails
  the schema (emitted by discovery/validation in core.py, reported
  through this family).
"""

from __future__ import annotations

from .core import Finding, ModelDecl, Registries, metric_registered

RULE_UNDECLARED_FAULT = "GM501"
RULE_UNKNOWN_METRIC = "GM502"
RULE_REGISTRY = "GM503"


def _known_actions(regs: Registries) -> set[str] | None:
    if regs.faults_sf is None:
        return None
    from tools.graftlint.registry import _literal_strset

    return _literal_strset(regs.faults_sf, "ACTIONS")


def check(decls: list[ModelDecl], regs: Registries) -> list[Finding]:
    out: list[Finding] = []

    # -- GM501/GM502: fault edges vs the fault plane and METRIC_DOCS ----
    for decl in decls:
        for i, tr in enumerate(decl.data.get("faults", [])):
            if not isinstance(tr, dict):
                continue
            line = decl.element_line(f"faults[{i}]")
            name = tr.get("name", f"faults[{i}]")
            site, action = tr.get("site"), tr.get("action")
            if isinstance(site, str) and isinstance(action, str):
                declared = regs.site_actions.get(site)
                if site not in regs.fault_sites:
                    out.append(Finding(
                        RULE_UNDECLARED_FAULT, decl.sf.rel, line,
                        f"model '{decl.name}': fault edge '{name}' uses "
                        f"site '{site}' not declared in FAULT_SITES",
                    ))
                elif declared is None or action not in {
                        a.strip() for a in declared.split(",")}:
                    out.append(Finding(
                        RULE_UNDECLARED_FAULT, decl.sf.rel, line,
                        f"model '{decl.name}': fault edge '{name}' uses "
                        f"action '{site}:{action}' not declared in "
                        f"SITE_ACTIONS",
                    ))
            metric = tr.get("metric")
            if isinstance(metric, str) and metric.strip() \
                    and not metric_registered(metric, regs.metric_docs):
                out.append(Finding(
                    RULE_UNKNOWN_METRIC, decl.sf.rel, line,
                    f"model '{decl.name}': fault edge '{name}' metric "
                    f"'{metric}' is not declared in METRIC_DOCS",
                ))

    # -- GM503: PROTOCOL_MODELS <-> model literals, both directions -----
    by_name: dict[str, list[ModelDecl]] = {}
    for decl in decls:
        by_name.setdefault(decl.name, []).append(decl)
    for mname, group in sorted(by_name.items()):
        for dup in group[1:]:
            out.append(Finding(
                RULE_REGISTRY, dup.sf.rel, dup.line,
                f"duplicate model name '{mname}' (also declared in "
                f"{group[0].sf.rel}:{group[0].line})",
            ))
    if regs.faults_sf is not None:
        frel = regs.faults_sf.rel
        for key in regs.protocol_models:
            if key not in by_name:
                out.append(Finding(
                    RULE_REGISTRY, frel,
                    regs.model_lines.get(key, 1),
                    f"PROTOCOL_MODELS entry '{key}' has no *_MODEL "
                    f"declaration with that name (dead registry entry)",
                ))
        for mname, group in sorted(by_name.items()):
            if mname not in regs.protocol_models:
                out.append(Finding(
                    RULE_REGISTRY, group[0].sf.rel, group[0].line,
                    f"model '{mname}' is not registered in "
                    f"PROTOCOL_MODELS (runtime/faults.py)",
                ))

        # -- SITE_ACTIONS <-> FAULT_SITES, both directions --------------
        actions = _known_actions(regs)
        for site, acts in regs.site_actions.items():
            sline = regs.site_lines.get(site, 1)
            if site not in regs.fault_sites:
                out.append(Finding(
                    RULE_REGISTRY, frel, sline,
                    f"SITE_ACTIONS site '{site}' is not declared in "
                    f"FAULT_SITES",
                ))
            if actions is not None:
                unknown = sorted(
                    {a.strip() for a in acts.split(",")} - actions)
                if unknown:
                    out.append(Finding(
                        RULE_REGISTRY, frel, sline,
                        f"SITE_ACTIONS['{site}'] declares actions "
                        f"{unknown} outside the fault plane's ACTIONS "
                        f"grammar",
                    ))
        for site in regs.fault_sites:
            if site not in regs.site_actions:
                out.append(Finding(
                    RULE_REGISTRY, frel,
                    regs.site_lines.get(site, 1),
                    f"FAULT_SITES site '{site}' has no SITE_ACTIONS "
                    f"declaration — every site must declare the actions "
                    f"its call site handles",
                ))
    return out
