"""GM1-GM3 — safety invariants over the explored state space, plus the
structural fallback-metric law.

- GM101: an invariant tagged ``GM1`` (ledger accounting: no
  double-charge, no lost refund, backstop-bounded metering) fails on a
  reachable state — reported with the shortest counterexample trace;
- GM201: an invariant tagged ``GM2`` (parcel ownership: every parked
  parcel owned by exactly one queued resume, budget conserved) fails;
- GM301: an invariant tagged ``GM3`` (at-most-once adoption, fallbacks
  counted exactly once) fails;
- GM302: a fault edge declares no ``metric`` — every failure edge must
  name the per-reason fallback counter its recovery path increments
  (GM502 then checks the name against METRIC_DOCS).

The three exploration rules share one BFS per model (run by
``run_project``); a violation message carries the violating state and
the shortest transition trace that reaches it, so the report IS the
reproduction.
"""

from __future__ import annotations

from .core import Finding, ModelDecl, _RULE_OF_TAG
from .machine import ExploreResult, render_state, render_trace

RULE_NO_METRIC = "GM302"

_FAMILY_TAGS = {"GM1", "GM2", "GM3"}


def check_explored(
        explored: list[tuple[ModelDecl, object, ExploreResult]],
) -> list[Finding]:
    out: list[Finding] = []
    for decl, _cm, res in explored:
        for v in res.violations:
            if v.kind != "invariant" or v.rule_tag not in _FAMILY_TAGS:
                continue
            out.append(Finding(
                _RULE_OF_TAG[v.rule_tag], decl.sf.rel,
                decl.element_line(v.key),
                f"model '{decl.name}': invariant '{v.name}' violated at "
                f"state [{render_state(v.state)}] — trace: "
                f"{render_trace(v.trace)}",
            ))
    return out


def check_metrics_declared(decls: list[ModelDecl]) -> list[Finding]:
    out: list[Finding] = []
    for decl in decls:
        for i, tr in enumerate(decl.data.get("faults", [])):
            if not isinstance(tr, dict):
                continue
            metric = tr.get("metric")
            if isinstance(metric, str) and metric.strip():
                continue
            out.append(Finding(
                RULE_NO_METRIC, decl.sf.rel,
                decl.element_line(f"faults[{i}]"),
                f"model '{decl.name}': fault edge "
                f"'{tr.get('name', f'faults[{i}]')}' declares no fallback "
                f"metric — every failure edge must name the per-reason "
                f"counter its recovery path increments",
            ))
    return out
