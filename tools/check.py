"""Unified static-analysis front door: ``python -m tools.check``.

Runs BOTH checkers over the repo and merges their exit codes:

- graftlint (tools/graftlint) — AST rules GL1xx-GL5xx;
- graftcheck (tools/graftcheck) — semantic contracts GC1xx-GC5xx + GCD.

One deliberate escalation over running them separately: a STALE baseline
entry (accepted debt whose finding no longer occurs) is an ERROR here, not
a warning.  Debt that got fixed must leave the baseline in the same PR —
run the matching ``--baseline-write`` to prune — or the baseline rots into
a list nobody can audit.

Exit status: 0 = both clean and no stale entries; 1 = new findings or
stale entries anywhere; 2 = usage error.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="python -m tools.check",
        description="run graftlint + graftcheck with merged exit codes",
    )
    ap.add_argument("--root", default=".", help="repo root to analyze")
    args = ap.parse_args(argv)
    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"check: --root {root} is not a directory", file=sys.stderr)
        return 2

    rc = 0

    # -- graftlint (AST) ---------------------------------------------------
    from tools import graftlint
    from tools.graftlint.core import stale_entries

    project = graftlint.load_project(root)
    lint_findings = graftlint.run_project(project)
    lint_baseline = graftlint.read_baseline(root)
    lint_new, lint_old = graftlint.split_new(lint_findings, lint_baseline)
    for f in lint_new:
        print(f.render())
    lint_stale = stale_entries(lint_findings, lint_baseline)
    print(f"check: graftlint: {len(lint_new)} new, {len(lint_old)} "
          f"baselined, {len(lint_stale)} stale", file=sys.stderr)

    # -- graftcheck (semantic) ---------------------------------------------
    from tools import graftcheck

    check_findings = graftcheck.run_all(root=root)
    check_baseline = graftcheck.read_baseline(root)
    check_new, check_old = graftcheck.split_new(
        check_findings, check_baseline)
    for f in check_new:
        print(f.render())
    check_stale = stale_entries(check_findings, check_baseline)
    print(f"check: graftcheck: {len(check_new)} new, {len(check_old)} "
          f"baselined, {len(check_stale)} stale", file=sys.stderr)

    if lint_new or check_new:
        rc = 1
    if lint_stale or check_stale:
        # Fixed debt MUST be pruned in the same change — stale entries are
        # errors at the front door (the standalone CLIs only warn).
        rc = 1
        for s in lint_stale:
            print(f"check: STALE graftlint baseline entry (fixed debt — "
                  f"prune with python -m tools.graftlint --baseline-write):"
                  f"\n  {s}", file=sys.stderr)
        for s in check_stale:
            print(f"check: STALE graftcheck baseline entry (fixed debt — "
                  f"prune with python -m tools.graftcheck --baseline-write):"
                  f"\n  {s}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
