"""Unified static-analysis front door: ``python -m tools.check``.

Runs ALL FIVE checkers over the repo and merges their exit codes:

- graftlint  (tools/graftlint)  — AST rules GL1xx-GL5xx;
- graftcheck (tools/graftcheck) — semantic contracts GC1xx-GC5xx + GCD;
- graftflow  (tools/graftflow)  — CFG/dataflow rules GF1xx-GF4xx + GFD;
- graftsync  (tools/graftsync)  — lockstep taint rules GS1xx-GS4xx + GSD;
- graftmodel (tools/graftmodel) — protocol model checking GM1xx-GM6xx
  + GMD.

``--only`` scopes a run to rule families ACROSS the tools
(``--only GF2,GC4,GM1``): tools with no selected family are skipped
entirely (graftcheck's tracing is the expensive one), and baseline /
stale accounting is filtered to the selected families so a scoped run
never mis-reports out-of-scope debt as stale.

One deliberate escalation over running the tools separately: a STALE
baseline entry (accepted debt whose finding no longer occurs) is an
ERROR here, not a warning.  Debt that got fixed must leave the baseline
in the same PR — run the matching ``--baseline-write`` to prune — or the
baseline rots into a list nobody can audit.

Per-tool wall time prints on stderr (the ``analysis-wall`` bench row
stamps the same numbers into BASELINE.md so the gate's cost stays
visible).

Exit status: 0 = all clean and no stale entries; 1 = new findings or
stale entries anywhere; 2 = usage error.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
import time
from pathlib import Path

# family token -> owning tool.  A finding's family is rule[:3]
# ("GL301" -> "GL3", "GCD01" -> "GCD", "GF201" -> "GF2").
FAMILIES = {
    **{f"GL{i}": "graftlint" for i in range(1, 6)},
    **{f"GC{i}": "graftcheck" for i in range(1, 6)}, "GCD": "graftcheck",
    **{f"GF{i}": "graftflow" for i in range(1, 5)}, "GFD": "graftflow",
    **{f"GS{i}": "graftsync" for i in range(1, 5)}, "GSD": "graftsync",
    **{f"GM{i}": "graftmodel" for i in range(1, 7)}, "GMD": "graftmodel",
}

_BASELINE_RULE_RE = re.compile(r":\s*(G[A-Z]{1,2}\d+)\b")


def _family(rule: str) -> str:
    return rule[:3]


def _filter_findings(findings, only):
    if only is None:
        return findings
    return [f for f in findings if _family(f.rule) in only]


def _filter_baseline(baseline: dict, only) -> dict:
    """Keep only baseline entries whose rule family is in scope — an
    out-of-scope entry must read neither as absorbing capacity nor as
    stale debt during a scoped run."""
    if only is None:
        return baseline
    out = {}
    for key, n in baseline.items():
        m = _BASELINE_RULE_RE.search(key)
        if m is not None and _family(m.group(1)) in only:
            out[key] = n
    return out


def _report(tool: str, findings, baseline, only, wall_s: float):
    """-> (new findings, stale entries) after family filtering."""
    from tools.graftlint.core import split_new, stale_entries

    findings = _filter_findings(findings, only)
    baseline = _filter_baseline(baseline, only)
    new, old = split_new(findings, baseline)
    for f in new:
        print(f.render())
    stale = stale_entries(findings, baseline)
    print(f"check: {tool}: {len(new)} new, {len(old)} baselined, "
          f"{len(stale)} stale ({wall_s:.1f}s)", file=sys.stderr)
    for s in stale:
        print(f"check: STALE {tool} baseline entry (fixed debt — prune "
              f"with python -m tools.{tool} --baseline-write):\n  {s}",
              file=sys.stderr)
    return new, stale


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="python -m tools.check",
        description="run graftlint + graftcheck + graftflow + graftsync "
                    "+ graftmodel with merged exit codes",
    )
    ap.add_argument("--root", default=".", help="repo root to analyze")
    ap.add_argument("--only", default=None,
                    help="comma-separated rule families across all tools, "
                         "e.g. GF2,GC4,GL3; tools with no selected family "
                         "are skipped")
    args = ap.parse_args(argv)
    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"check: --root {root} is not a directory", file=sys.stderr)
        return 2

    only = None
    if args.only:
        only = {s.strip() for s in args.only.split(",") if s.strip()}
        unknown = only - set(FAMILIES)
        if unknown:
            print(f"check: unknown families {sorted(unknown)}; have "
                  f"{sorted(FAMILIES)}", file=sys.stderr)
            return 2

    def want(tool: str) -> bool:
        return only is None or any(FAMILIES[f] == tool for f in only)

    t_start = time.perf_counter()
    rc = 0
    walls: list[tuple[str, float]] = []

    # -- graftlint (AST) ---------------------------------------------------
    if want("graftlint"):
        from tools import graftlint

        t0 = time.perf_counter()
        project = graftlint.load_project(root)
        findings = graftlint.run_project(project)
        wall = time.perf_counter() - t0
        walls.append(("graftlint", wall))
        new, stale = _report("graftlint", findings,
                             graftlint.read_baseline(root), only, wall)
        rc |= 1 if (new or stale) else 0

    # -- graftflow (CFG/dataflow) ------------------------------------------
    if want("graftflow"):
        from tools import graftflow

        t0 = time.perf_counter()
        gf_only = ({f for f in only if FAMILIES[f] == "graftflow"}
                   if only is not None else None)
        findings = graftflow.run_project(graftflow.load_project(root),
                                         only=gf_only)
        wall = time.perf_counter() - t0
        walls.append(("graftflow", wall))
        new, stale = _report("graftflow", findings,
                             graftflow.read_baseline(root), only, wall)
        rc |= 1 if (new or stale) else 0

    # -- graftsync (lockstep taint) ----------------------------------------
    if want("graftsync"):
        from tools import graftsync

        t0 = time.perf_counter()
        gs_only = ({f for f in only if FAMILIES[f] == "graftsync"}
                   if only is not None else None)
        findings = graftsync.run_project(graftsync.load_project(root),
                                         only=gs_only)
        wall = time.perf_counter() - t0
        walls.append(("graftsync", wall))
        new, stale = _report("graftsync", findings,
                             graftsync.read_baseline(root), only, wall)
        rc |= 1 if (new or stale) else 0

    # -- graftmodel (protocol model checking) ------------------------------
    if want("graftmodel"):
        from tools import graftmodel

        t0 = time.perf_counter()
        gm_only = ({f for f in only if FAMILIES[f] == "graftmodel"}
                   if only is not None else None)
        findings = graftmodel.run_project(graftmodel.load_project(root),
                                          only=gm_only)
        wall = time.perf_counter() - t0
        walls.append(("graftmodel", wall))
        new, stale = _report("graftmodel", findings,
                             graftmodel.read_baseline(root), only, wall)
        rc |= 1 if (new or stale) else 0

    # -- graftcheck (semantic; imports + traces, the expensive one) --------
    if want("graftcheck"):
        from tools import graftcheck

        t0 = time.perf_counter()
        gc_only = ({f for f in only if FAMILIES[f] == "graftcheck"}
                   if only is not None else None)
        findings = graftcheck.run_all(only=gc_only, root=root)
        wall = time.perf_counter() - t0
        walls.append(("graftcheck", wall))
        new, stale = _report("graftcheck", findings,
                             graftcheck.read_baseline(root), only, wall)
        rc |= 1 if (new or stale) else 0

    total = time.perf_counter() - t_start
    per_tool = " ".join(f"{t}={w:.1f}s" for t, w in walls)
    print(f"check: wall {per_tool} total={total:.1f}s", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
