#!/usr/bin/env python
"""On-device kernel parity check: the first thing the TPU runbook runs.

Compiles BOTH Pallas kernels (fused dequant-matmul, flash attention) on the
default JAX backend and compares against the einsum/dense references.
Interpret-mode CI (tests/ops/) proves the kernels' *programs*; this script
proves Mosaic *lowering* — tiling, VMEM budgets, sublane int4 unpack — which
interpret mode cannot catch.  Exit 0 = all parities hold compiled on this
backend; exit 1 = mismatch or lowering failure (stack trace printed).

Run via tools/tpu_runbook.sh; standalone: `python tools/kernel_parity.py`.
"""
from __future__ import annotations

import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("DLT_PARITY_CPU") == "1":
    # The axon plugin ignores JAX_PLATFORMS=cpu; pin via jax.config BEFORE
    # the first backend query or a dead tunnel wedges this script ~25 min.
    jax.config.update("jax_platforms", "cpu")

# TPU: force the compiled-kernel path (never a silent fallback "pass").
# Elsewhere: interpret mode — validates this script's own logic, proves
# nothing about Mosaic lowering (the runbook only fires it on TPU).
ON_TPU = jax.default_backend() == "tpu"
os.environ["DLT_QUANT_MATMUL"] = "kernel" if ON_TPU else "interpret"

import jax.numpy as jnp
import numpy as np

from distributed_llms_tpu.checkpoint.quantize import dequantize, quantize
from distributed_llms_tpu.ops import decode_attn
from distributed_llms_tpu.ops.flash import _dense_reference, flash_attention
from distributed_llms_tpu.ops.quant_matmul import quant_contract

os.environ["DLT_RAGGED_DECODE"] = "kernel" if ON_TPU else "interpret"


def check(name: str, got, want, rtol: float, atol: float) -> None:
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)
    err = float(np.max(np.abs(got - want)))
    print(f"  PASS {name:40s} max|err|={err:.3e}")


def quant_parity() -> None:
    key = jax.random.PRNGKey(0)
    for bits in (8, 4):
        for m, k, n in ((8, 1024, 2048), (4, 4096, 4096)):
            kx, kw = jax.random.split(jax.random.fold_in(key, bits * 100 + m))
            x = jax.random.normal(kx, (m, k), jnp.bfloat16)
            w = jax.random.normal(kw, (k, n), jnp.float32) / np.sqrt(k)
            qt = quantize(w, bits=bits)
            got = jax.jit(lambda x, qt: quant_contract(x, qt, k_lead=1))(x, qt)
            want = jnp.asarray(x, jnp.float32) @ dequantize(qt, jnp.float32)
            # bf16 activations: kernel accumulates f32 but inputs quantize the
            # signal; match the suite's bf16 tolerance.
            check(f"quant int{bits} [{m}x{k}]@[{k}x{n}]", got, want,
                  rtol=2e-2, atol=2e-2)


def flash_parity() -> None:
    key = jax.random.PRNGKey(1)
    for b, t, s, h, kvh, d in ((2, 512, 512, 8, 4, 128), (1, 2048, 2048, 8, 8, 128)):
        ks = jax.random.split(jax.random.fold_in(key, t), 3)
        q = jax.random.normal(ks[0], (b, t, h, d), jnp.bfloat16)
        kk = jax.random.normal(ks[1], (b, s, kvh, d), jnp.bfloat16)
        v = jax.random.normal(ks[2], (b, s, kvh, d), jnp.bfloat16)
        got = jax.jit(
            lambda q, k, v: flash_attention(q, k, v, causal=True,
                                            interpret=not ON_TPU)
        )(q, kk, v)
        want = _dense_reference(q, kk, v, None, None, None, True)
        check(f"flash causal B{b} T{t} S{s} H{h}/{kvh}", got, want,
              rtol=3e-2, atol=3e-2)
    # Sliding-window band (Mistral/Phi-3 prefill): dead-tile clamping +
    # boundary iota masks on both edges must survive Mosaic lowering.
    ks = jax.random.split(jax.random.fold_in(key, 9), 3)
    b, t, h, kvh, d, win = 1, 2048, 8, 4, 128, 512
    q = jax.random.normal(ks[0], (b, t, h, d), jnp.bfloat16)
    kk = jax.random.normal(ks[1], (b, t, kvh, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, t, kvh, d), jnp.bfloat16)
    got = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, causal=True, window=win,
                                        interpret=not ON_TPU)
    )(q, kk, v)
    want = _dense_reference(q, kk, v, None, None, None, True, win)
    check(f"flash windowed T{t} win{win}", got, want, rtol=3e-2, atol=3e-2)


def paged_parity() -> None:
    key = jax.random.PRNGKey(3)
    b, pool, blk, pages, h, kvh, d = 4, 48, 128, 8, 8, 4, 128
    rng = np.random.RandomState(0)
    tables = jnp.asarray(
        rng.permutation(pool)[: b * pages].reshape(b, pages), jnp.int32
    )
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, 1, h, d), jnp.bfloat16)
    k_rows = jax.random.normal(ks[1], (b, pages * blk, kvh, d), jnp.bfloat16)
    v_rows = jax.random.normal(ks[2], (b, pages * blk, kvh, d), jnp.bfloat16)
    k_pool = jnp.zeros((pool, blk, kvh, d), jnp.bfloat16).at[
        tables.reshape(-1)
    ].set(k_rows.reshape(b * pages, blk, kvh, d))
    v_pool = jnp.zeros((pool, blk, kvh, d), jnp.bfloat16).at[
        tables.reshape(-1)
    ].set(v_rows.reshape(b * pages, blk, kvh, d))
    ln = jnp.asarray([1, 300, pages * blk, 129], jnp.int32)
    got = jax.jit(decode_attn.paged_decode_attention)(
        q, k_pool, v_pool, ln, tables
    )
    want = decode_attn._dense_reference(q, k_rows, v_rows, ln)
    check(f"paged decode B{b} pool{pool} blk{blk}", got, want,
          rtol=3e-2, atol=3e-2)


def ragged_parity() -> None:
    key = jax.random.PRNGKey(2)
    for b, s, h, kvh, d, lengths in (
        (4, 512, 8, 4, 128, (3, 200, 512, 64)),
        (2, 2048, 8, 8, 128, (1500, 2048)),
    ):
        ks = jax.random.split(jax.random.fold_in(key, s), 3)
        q = jax.random.normal(ks[0], (b, 1, h, d), jnp.bfloat16)
        kk = jax.random.normal(ks[1], (b, s, kvh, d), jnp.bfloat16)
        v = jax.random.normal(ks[2], (b, s, kvh, d), jnp.bfloat16)
        ln = jnp.asarray(lengths, jnp.int32)
        got = jax.jit(decode_attn.ragged_decode_attention)(q, kk, v, ln)
        want = decode_attn._dense_reference(q, kk, v, ln)
        check(f"ragged decode B{b} S{s} H{h}/{kvh}", got, want,
              rtol=3e-2, atol=3e-2)
    # Sliding-window band: first/last block clamps + in-block mask.
    ks = jax.random.split(jax.random.fold_in(key, 11), 3)
    b, s, h, kvh, d, win = 2, 2048, 8, 4, 128, 300
    q = jax.random.normal(ks[0], (b, 1, h, d), jnp.bfloat16)
    kk = jax.random.normal(ks[1], (b, s, kvh, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, kvh, d), jnp.bfloat16)
    ln = jnp.asarray([1900, 64], jnp.int32)
    got = jax.jit(functools.partial(
        decode_attn.ragged_decode_attention, window=win
    ))(q, kk, v, ln)
    want = decode_attn._dense_reference(q, kk, v, ln, window=win)
    check(f"ragged windowed S{s} win{win}", got, want, rtol=3e-2, atol=3e-2)


def decode_int8_parity() -> None:
    """Int8 KV-page legs (--kv-bits 8): the scale-fused kernels vs the
    dequantize-then-dense reference (checkpoint.quantize.kv_dequantize
    numerics — exactly what the CPU fallback computes)."""
    from distributed_llms_tpu.checkpoint.quantize import (kv_dequantize,
                                                          kv_quantize)

    key = jax.random.PRNGKey(7)
    # Ragged leg.
    b, s, h, kvh, d = 4, 512, 8, 4, 128
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, 1, h, d), jnp.bfloat16)
    kk = jax.random.normal(ks[1], (b, s, kvh, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, kvh, d), jnp.bfloat16)
    kq, ksc = kv_quantize(kk)
    vq, vsc = kv_quantize(v)
    ln = jnp.asarray([3, 200, 512, 64], jnp.int32)
    got = jax.jit(decode_attn.ragged_decode_attention)(
        q, kq, vq, ln, k_scale=ksc, v_scale=vsc
    )
    want = decode_attn._dense_reference(
        q, kv_dequantize(kq, ksc, q.dtype), kv_dequantize(vq, vsc, q.dtype),
        ln,
    )
    check(f"ragged int8 B{b} S{s}", got, want, rtol=3e-2, atol=3e-2)
    # Paged leg.
    pool, blk, pages = 48, 128, 4
    rng = np.random.RandomState(1)
    tables = jnp.asarray(
        rng.permutation(pool)[: b * pages].reshape(b, pages), jnp.int32
    )
    k_pool = jnp.zeros((pool, blk, kvh, d), jnp.int8).at[
        tables.reshape(-1)
    ].set(kq[:, : pages * blk].reshape(b * pages, blk, kvh, d))
    v_pool = jnp.zeros((pool, blk, kvh, d), jnp.int8).at[
        tables.reshape(-1)
    ].set(vq[:, : pages * blk].reshape(b * pages, blk, kvh, d))
    ks_pool = jnp.ones((pool, blk, kvh), jnp.float32).at[
        tables.reshape(-1)
    ].set(ksc[:, : pages * blk].reshape(b * pages, blk, kvh))
    vs_pool = jnp.ones((pool, blk, kvh), jnp.float32).at[
        tables.reshape(-1)
    ].set(vsc[:, : pages * blk].reshape(b * pages, blk, kvh))
    ln = jnp.asarray([1, 300, pages * blk, 129], jnp.int32)
    got = jax.jit(decode_attn.paged_decode_attention)(
        q, k_pool, v_pool, ln, tables, k_scale=ks_pool, v_scale=vs_pool
    )
    want = decode_attn._dense_reference(
        q,
        kv_dequantize(kq[:, : pages * blk], ksc[:, : pages * blk], q.dtype),
        kv_dequantize(vq[:, : pages * blk], vsc[:, : pages * blk], q.dtype),
        ln,
    )
    check(f"paged int8 B{b} pool{pool} blk{blk}", got, want,
          rtol=3e-2, atol=3e-2)


def main() -> int:
    backend = jax.default_backend()
    print(f"kernel_parity: backend={backend} devices={jax.device_count()}")
    if backend != "tpu":
        print(f"  WARNING: backend={backend} — running kernels in INTERPRET "
              "mode (validates this script, NOT Mosaic lowering).")
    quant_parity()
    flash_parity()
    ragged_parity()
    paged_parity()
    decode_int8_parity()
    mode = "compiled" if ON_TPU else "interpret"
    # v3: round 12 added the int8 KV-page legs (scale-fused decode) —
    # versioning the marker makes tools/tpu_runbook.sh re-run the sweep on
    # the next TPU window instead of skipping on a pre-window PARITY_TPU.log.
    print(f"kernel_parity: ALL PASS v3 ({mode}, backend={backend})")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception:
        import traceback

        traceback.print_exc()
        print("kernel_parity: FAIL")
        sys.exit(1)
