#!/usr/bin/env python
"""Entry point: coordinator + REPL (run_master.py parity).  See
distributed_llms_tpu/cli/coordinator_main.py."""

from distributed_llms_tpu.cli.coordinator_main import main

if __name__ == "__main__":
    main()
